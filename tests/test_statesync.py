"""Statesync: snapshot bootstrap with a lite2-verified trust root.

Tiers covered here:
  * ABCI snapshot wire types + socket/gRPC transport conformance (the
    four methods must round-trip identically on both transports);
  * kvstore snapshot production/restore (hash-addressed chunks, bad
    chunks rejected, restored app == original app);
  * ChunkScheduler FSM (spread, timeout requeue, bad-hash different-peer
    refetch + ban, retry exhaustion);
  * EngineCommitPreverify (one verify_many arrival per commit);
  * live-net bootstrap: an empty 4th node joins a 3-validator net via
    snapshot restore (verified against a lite2 trust root over real RPC),
    then follows consensus — plus crash-during-restore recovery and the
    malicious-peer ban path.
"""

import asyncio
import hashlib

import pytest

from tendermint_tpu.abci import types as t
from tendermint_tpu.abci.examples import KVStoreApplication
from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.statesync.chunker import ChunkScheduler
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "statesync-chain"

SNAP_METHODS = ("list_snapshots", "offer_snapshot", "load_snapshot_chunk", "apply_snapshot_chunk")


def _seeded_app(**kw) -> KVStoreApplication:
    """A kvstore with a few committed heights and a snapshot at 4."""
    app = KVStoreApplication(snapshot_interval=4, snapshot_chunk_bytes=128, **kw)
    for h in range(4):
        app.deliver_tx(t.RequestDeliverTx(tx=b"key%d=val%d" % (h, h)))
        app.commit()
    return app


# ---------------------------------------------------------------------------
# wire + transport conformance
# ---------------------------------------------------------------------------


class TestSnapshotWire:
    def test_roundtrip(self):
        import msgpack

        snap = t.Snapshot(height=7, format=1, chunks=3, hash=b"h" * 32, metadata=b"meta")
        pairs = [
            ("list_snapshots", t.RequestListSnapshots(), t.ResponseListSnapshots([snap])),
            (
                "offer_snapshot",
                t.RequestOfferSnapshot(snapshot=snap, app_hash=b"a" * 32),
                t.ResponseOfferSnapshot(result=t.OfferSnapshotResult.ACCEPT),
            ),
            (
                "load_snapshot_chunk",
                t.RequestLoadSnapshotChunk(height=7, format=1, chunk=2),
                t.ResponseLoadSnapshotChunk(chunk=b"bytes"),
            ),
            (
                "apply_snapshot_chunk",
                t.RequestApplySnapshotChunk(index=2, chunk=b"bytes", sender="p1"),
                t.ResponseApplySnapshotChunk(
                    result=t.ApplySnapshotChunkResult.RETRY,
                    refetch_chunks=[2],
                    reject_senders=["p1"],
                ),
            ),
        ]
        for kind, req, resp in pairs:
            for direction, msg in ((0, req), (1, resp)):
                raw = msgpack.packb(t.encode_msg(kind, msg), use_bin_type=True)
                k2, m2 = t.decode_msg(msgpack.unpackb(raw, raw=False), direction)
                assert k2 == kind and m2 == msg


class TestSnapshotTransportParity:
    """Satellite: socket and gRPC must agree on the four snapshot methods'
    encode/decode round-trip, so the new types can't drift between
    transports (mirrors the abci/grpc parity tests)."""

    @pytest.mark.parametrize("method", SNAP_METHODS)
    async def test_transports_agree(self, method, tmp_path):
        from tendermint_tpu.abci.client import SocketClient
        from tendermint_tpu.abci.grpc import GRPCClient, GRPCServer
        from tendermint_tpu.abci.server import SocketServer

        async def drive(client, app):
            snap = app.list_snapshots(t.RequestListSnapshots()).snapshots[-1]
            if method == "list_snapshots":
                res = await client.list_snapshots(t.RequestListSnapshots())
                return [vars(s) for s in res.snapshots]
            if method == "offer_snapshot":
                res = await client.offer_snapshot(
                    t.RequestOfferSnapshot(snapshot=snap, app_hash=app.app_hash)
                )
                return vars(res)
            if method == "load_snapshot_chunk":
                res = await client.load_snapshot_chunk(
                    t.RequestLoadSnapshotChunk(height=snap.height, format=snap.format, chunk=0)
                )
                return vars(res)
            # apply_snapshot_chunk: offer to a FRESH app then apply chunk 0
            await client.offer_snapshot(
                t.RequestOfferSnapshot(snapshot=snap, app_hash=app.app_hash)
            )
            chunk = app.db.get(b"__snapchunk__:%016d:%08d" % (snap.height, 0))
            res = await client.apply_snapshot_chunk(
                t.RequestApplySnapshotChunk(index=0, chunk=chunk, sender="peerZ")
            )
            return vars(res)

        # socket
        sock_path = str(tmp_path / "abci.sock")
        app_s = _seeded_app()
        server_s = SocketServer(f"unix://{sock_path}", app_s)
        await server_s.start()
        client_s = SocketClient(f"unix://{sock_path}")
        await client_s.start()
        try:
            socket_result = await drive(client_s, app_s)
        finally:
            await client_s.stop()
            await server_s.stop()

        # grpc
        app_g = _seeded_app()
        server_g = GRPCServer("127.0.0.1:0", app_g)
        await server_g.start()
        client_g = GRPCClient(server_g.bound_addr)
        await client_g.start()
        try:
            grpc_result = await drive(client_g, app_g)
        finally:
            await client_g.stop()
            await server_g.stop()

        assert socket_result == grpc_result


# ---------------------------------------------------------------------------
# kvstore snapshots
# ---------------------------------------------------------------------------


class TestKVStoreSnapshots:
    def test_take_list_prune(self):
        app = KVStoreApplication(snapshot_interval=2, snapshot_keep_recent=2)
        for _ in range(8):
            app.deliver_tx(t.RequestDeliverTx(tx=b"x=y"))
            app.commit()
        heights = [s.height for s in app.list_snapshots(t.RequestListSnapshots()).snapshots]
        assert heights == [6, 8]  # pruned to the 2 most recent

    def test_restore_reproduces_state(self):
        app = _seeded_app()
        snap = app.list_snapshots(t.RequestListSnapshots()).snapshots[-1]
        chunks = [
            app.load_snapshot_chunk(
                t.RequestLoadSnapshotChunk(height=snap.height, format=snap.format, chunk=i)
            ).chunk
            for i in range(snap.chunks)
        ]
        assert snap.chunks > 1  # 128-byte chunks force a real multi-chunk path
        app2 = KVStoreApplication()
        res = app2.offer_snapshot(t.RequestOfferSnapshot(snapshot=snap, app_hash=app.app_hash))
        assert res.result == t.OfferSnapshotResult.ACCEPT
        for i, c in enumerate(chunks):
            res = app2.apply_snapshot_chunk(t.RequestApplySnapshotChunk(index=i, chunk=c))
            assert res.result == t.ApplySnapshotChunkResult.ACCEPT
        assert (app2.height, app2.tx_count, app2.app_hash) == (
            app.height, app.tx_count, app.app_hash,
        )
        assert app2.query(t.RequestQuery(data=b"key2")).value == b"val2"

    def test_bad_chunk_hash_names_sender(self):
        app = _seeded_app()
        snap = app.list_snapshots(t.RequestListSnapshots()).snapshots[-1]
        app2 = KVStoreApplication()
        app2.offer_snapshot(t.RequestOfferSnapshot(snapshot=snap, app_hash=app.app_hash))
        res = app2.apply_snapshot_chunk(
            t.RequestApplySnapshotChunk(index=0, chunk=b"poison", sender="evil-peer")
        )
        assert res.result == t.ApplySnapshotChunkResult.RETRY
        assert res.refetch_chunks == [0]
        assert res.reject_senders == ["evil-peer"]

    def test_wrong_app_hash_rejected_and_wiped(self):
        app = _seeded_app()
        snap = app.list_snapshots(t.RequestListSnapshots()).snapshots[-1]
        chunks = [
            app.load_snapshot_chunk(
                t.RequestLoadSnapshotChunk(height=snap.height, format=snap.format, chunk=i)
            ).chunk
            for i in range(snap.chunks)
        ]
        app2 = KVStoreApplication()
        app2.offer_snapshot(
            t.RequestOfferSnapshot(snapshot=snap, app_hash=b"\x13" * 32)  # wrong
        )
        for i, c in enumerate(chunks[:-1]):
            assert (
                app2.apply_snapshot_chunk(t.RequestApplySnapshotChunk(index=i, chunk=c)).result
                == t.ApplySnapshotChunkResult.ACCEPT
            )
        res = app2.apply_snapshot_chunk(
            t.RequestApplySnapshotChunk(index=snap.chunks - 1, chunk=chunks[-1])
        )
        assert res.result == t.ApplySnapshotChunkResult.REJECT_SNAPSHOT
        assert app2.height == 0 and app2.db.get(b"kv:key1") is None  # no bad-state accept

    def test_bad_metadata_rejected_at_offer(self):
        app2 = KVStoreApplication()
        snap = t.Snapshot(height=4, format=1, chunks=2, hash=b"z" * 32, metadata=b"junk")
        res = app2.offer_snapshot(t.RequestOfferSnapshot(snapshot=snap, app_hash=b"a" * 32))
        assert res.result == t.OfferSnapshotResult.REJECT
        res = app2.offer_snapshot(
            t.RequestOfferSnapshot(
                snapshot=t.Snapshot(height=4, format=9, chunks=1, hash=b"z" * 32), app_hash=b""
            )
        )
        assert res.result == t.OfferSnapshotResult.REJECT_FORMAT


# ---------------------------------------------------------------------------
# chunk scheduler FSM
# ---------------------------------------------------------------------------


def _hashes(*chunks: bytes):
    return [hashlib.sha256(c).digest() for c in chunks]


class TestChunkScheduler:
    def test_spreads_and_completes(self):
        chunks = [b"a", b"b", b"c", b"d"]
        sched = ChunkScheduler(_hashes(*chunks), max_inflight_per_peer=2)
        sched.add_peer("p1")
        sched.add_peer("p2")
        reqs = sched.next_requests(0.0)
        for peer, idx in reqs:
            sched.mark_requested(peer, idx, 0.0)
        assert sorted(i for _, i in reqs) == [0, 1, 2, 3]
        assert {p for p, _ in reqs} == {"p1", "p2"}  # spread, not one peer
        for peer, idx in reqs:
            assert sched.chunk_received(peer, idx, chunks[idx], 0.1) == "ok"
        applied = []
        while (item := sched.next_apply()) is not None:
            applied.append(item[0])
            sched.mark_applied(item[0])
        assert applied == [0, 1, 2, 3] and sched.done()

    def test_timeout_requeues_with_backoff(self):
        sched = ChunkScheduler(_hashes(b"a"), timeout=1.0, max_retries=2)
        sched.add_peer("p1")
        sched.mark_requested("p1", 0, 0.0)
        assert sched.next_requests(0.5) == []  # in flight
        reqs = sched.next_requests(2.0)  # timed out -> backoff, then requeue
        assert sched.retries[0] == 1
        later = sched.next_requests(10.0)
        assert later == [("p1", 0)]

    def test_bad_hash_bans_and_prefers_other_peer(self):
        sched = ChunkScheduler(_hashes(b"a"), max_retries=3)
        sched.add_peer("bad")
        sched.add_peer("good")
        sched.mark_requested("bad", 0, 0.0)
        assert sched.chunk_received("bad", 0, b"poison", 0.1) == "bad_hash"
        assert "bad" in sched.banned
        reqs = sched.next_requests(10.0)
        assert reqs == [("good", 0)]  # refetch from a different peer
        sched.mark_requested("good", 0, 10.0)
        assert sched.chunk_received("good", 0, b"a", 10.1) == "ok"

    def test_unsolicited_and_dup(self):
        sched = ChunkScheduler(_hashes(b"a", b"b"))
        sched.add_peer("p1")
        assert sched.chunk_received("p1", 0, b"a", 0.0) == "unsolicited"
        sched.mark_requested("p1", 0, 0.0)
        assert sched.chunk_received("p2", 0, b"a", 0.1) == "unsolicited"
        assert sched.chunk_received("p1", 0, b"a", 0.1) == "ok"
        assert sched.chunk_received("p1", 0, b"a", 0.2) == "dup"

    def test_retry_exhaustion_fails(self):
        sched = ChunkScheduler(_hashes(b"a"), timeout=0.1, max_retries=1)
        sched.add_peer("p1")
        now = 0.0
        for _ in range(10):
            if sched.is_failed():
                break
            for peer, idx in sched.next_requests(now):
                sched.mark_requested(peer, idx, now)
            now += 10.0
        assert sched.is_failed()

    def test_no_peers_is_failure(self):
        sched = ChunkScheduler(_hashes(b"a"))
        sched.add_peer("p1")
        assert not sched.is_failed()
        sched.remove_peer("p1")
        assert sched.is_failed()


# ---------------------------------------------------------------------------
# engine pre-verification adapter
# ---------------------------------------------------------------------------


class TestEngineCommitPreverify:
    async def test_one_arrival_per_commit_and_correct_results(self):
        """The adapter must enqueue the whole commit as ONE verify_many
        call and the returned batch_verify must serve verify_commit."""
        from tendermint_tpu.statesync.syncer import EngineCommitPreverify
        from tests.test_lite2 import CHAIN, make_chain, rand_vset

        vset, pvs = rand_vset(4)
        headers, _ = make_chain(5, {1: (vset, pvs)})
        sh = headers[5]
        vals = vset
        bid = sh.commit.block_id
        commit = sh.commit

        calls = []

        class FakeAsyncVerifier:
            def verify_many(self, items):
                calls.append(len(items))
                from tendermint_tpu.crypto.batch import host_batch_verify

                res = host_batch_verify(
                    [i[0] for i in items], [i[1] for i in items], [i[2] for i in items]
                )
                futs = []
                for ok in res:
                    f = asyncio.get_event_loop().create_future()
                    f.set_result(bool(ok))
                    futs.append(f)
                return futs

        pre = EngineCommitPreverify(FakeAsyncVerifier())
        bv = await pre(sh, [vals])
        assert len(calls) == 1 and calls[0] == 4  # one arrival, whole commit
        vals.verify_commit(CHAIN, bid, 5, commit, batch_verify=bv)  # passes
        # second pass hits the cache: no new arrivals
        bv2 = await pre(sh, [vals])
        assert len(calls) == 1
        vals.verify_commit(CHAIN, bid, 5, commit, batch_verify=bv2)


# ---------------------------------------------------------------------------
# live-net bootstrap
# ---------------------------------------------------------------------------


async def make_serving_net(tmp_path, n=3, snapshot_interval=4, name="ssnet"):
    """N validators with RPC on and app snapshots every `snapshot_interval`
    heights — the net a statesync joiner bootstraps from."""
    pvs = sorted([MockPV() for _ in range(n)], key=lambda pv: pv.address())
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = make_test_cfg(str(tmp_path / f"{name}{i}"))
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.base.db_backend = "memdb"
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.1
        cfg.statesync.snapshot_interval = snapshot_interval
        cfg.statesync.snapshot_chunk_bytes = 256  # force a multi-chunk restore
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        nodes.append(node)
    for node in nodes:
        await node.start()
    for i in range(n):
        for j in range(i + 1, n):
            addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
            await nodes[i].switch.dial_peer(addr)
    for _ in range(300):
        if all(node.switch.num_peers() == n - 1 for node in nodes):
            break
        await asyncio.sleep(0.01)
    return nodes, pvs, gen


async def wait_height(nodes, h, timeout=60.0):
    async def _wait():
        while not all(n.block_store.height() >= h for n in nodes):
            await asyncio.sleep(0.05)

    await asyncio.wait_for(_wait(), timeout)


def joiner_config(tmp_path, nodes, name="joiner", db="memdb"):
    """Statesync joiner config: trust root = header at height 2 from
    node0's store, trust servers = node0+node1 RPC."""
    cfg = make_test_cfg(str(tmp_path / name))
    cfg.rpc.laddr = ""
    cfg.base.db_backend = db
    cfg.base.fast_sync = True
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_commit = 0.1
    cfg.statesync.enable = True
    cfg.statesync.rpc_servers = ",".join(n.rpc_server.listen_addr for n in nodes[:2])
    cfg.statesync.trust_height = 2
    cfg.statesync.trust_hash = nodes[0].block_store.load_block_meta(2).header.hash().hex()
    cfg.statesync.discovery_time = 0.5
    cfg.statesync.chunk_fetch_timeout = 5.0
    cfg.validate_basic()
    return cfg


async def dial_all(joiner, nodes):
    for n in nodes:
        addr = f"{n.node_key.id}@{n.switch.transport.listen_addr}"
        await joiner.switch.dial_peer(addr)


class TestStateSyncBootstrap:
    async def test_empty_node_bootstraps_from_snapshot(self, tmp_path):
        """The acceptance path: a 4th empty node joins via snapshot
        restore (app hash checked against a lite2-verified header), hands
        over to fastsync, then follows consensus.  `earliest_block_height`
        proves it never replayed from genesis."""
        nodes, pvs, gen = await make_serving_net(tmp_path)
        joiner = None
        try:
            # a few txs so the snapshot payload spans multiple chunks
            for i in range(12):
                await nodes[0].mempool.check_tx(b"seed%d=%d" % (i, i))
            await wait_height(nodes, 7)

            cfg = joiner_config(tmp_path, nodes)
            joiner = Node(cfg, gen, priv_validator=None, db_backend="memdb")
            await joiner.start()
            assert joiner.statesync_reactor.syncing
            await dial_all(joiner, nodes)

            target = nodes[0].block_store.height() + 3

            async def synced():
                while joiner.block_store.height() < target:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)

            # never replayed from genesis: the store starts AT the snapshot
            base = joiner.block_store.base()
            assert base > 1, "joiner fell back to replay-from-genesis"
            assert base % 4 == 0  # a snapshot height
            # restored block hashes match the validators'
            h = target - 1
            assert (
                joiner.block_store.load_block(h).hash()
                == nodes[0].block_store.load_block(h).hash()
            )
            # recorder proves the offer→chunk→restore→handover chain
            from tendermint_tpu.libs import tracing

            events = joiner.flight_recorder.events()
            ms = tracing.statesync_bootstrap_ms(events)
            assert ms is not None and ms > 0.0
            kinds = [e["kind"] for e in events if e["kind"].startswith("statesync.")]
            assert kinds.count("statesync.chunk") >= 2  # multi-chunk restore
            # phase surfaced via RPC /status
            from tendermint_tpu.rpc.core import RPCCore

            status = await RPCCore(joiner).status()
            assert status["sync_info"]["sync_phase"] in ("fastsync", "caught_up")
            assert status["sync_info"]["earliest_block_height"] == base
        finally:
            if joiner is not None and joiner.is_running:
                await joiner.stop()
            for n in nodes:
                if n.is_running:
                    await n.stop()

    async def test_crash_mid_restore_then_recover(self, tmp_path):
        """Satellite: kill the joiner mid-chunk-restore; a restart on the
        same (sqlite) home must bootstrap cleanly — statesync persists
        nothing until the restore is verified, so the retry starts from an
        empty store instead of wedging."""
        nodes, pvs, gen = await make_serving_net(tmp_path, name="crashnet")
        joiner = None
        try:
            for i in range(12):
                await nodes[0].mempool.check_tx(b"cr%d=%d" % (i, i))
            await wait_height(nodes, 7)

            cfg = joiner_config(tmp_path, nodes, name="crash-joiner", db="sqlite")
            joiner = Node(cfg, gen, priv_validator=None)
            await joiner.start()
            # gate the apply path: chunk 0 applies, chunk 1 BLOCKS until
            # the kill lands — the restore is deterministically mid-flight
            # (discovery hasn't finished yet, so the syncer has not
            # grabbed the conn's method reference)
            conn = joiner.proxy_app.query()
            orig_apply = conn.apply_snapshot_chunk
            mid_restore = asyncio.Event()
            hold = asyncio.Event()  # never set; released by cancellation

            async def gated_apply(req):
                if req.index >= 1:
                    mid_restore.set()
                    await hold.wait()
                return await orig_apply(req)

            conn.apply_snapshot_chunk = gated_apply
            await dial_all(joiner, nodes)

            await asyncio.wait_for(mid_restore.wait(), 30.0)
            await joiner.stop()  # crash mid-restore
            assert joiner.block_store.height() == 0  # nothing persisted yet

            joiner = Node(cfg, gen, priv_validator=None)
            await joiner.start()
            assert joiner.statesync_reactor.syncing  # retries from empty
            await dial_all(joiner, nodes)
            target = nodes[0].block_store.height() + 2

            async def synced():
                while joiner.block_store.height() < target:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            assert joiner.block_store.base() > 1
        finally:
            if joiner is not None and joiner.is_running:
                await joiner.stop()
            for n in nodes:
                if n.is_running:
                    await n.stop()

    async def test_statesync_failure_falls_back_to_fastsync(self, tmp_path):
        """Unreachable trust servers: statesync must fail cleanly and the
        node must still join via fastsync-from-genesis — degraded, never
        wedged."""
        nodes, pvs, gen = await make_serving_net(tmp_path, name="fbnet")
        joiner = None
        try:
            await wait_height(nodes, 5)
            cfg = joiner_config(tmp_path, nodes, name="fb-joiner")
            cfg.statesync.rpc_servers = "127.0.0.1:1"  # nothing listens here
            cfg.statesync.discovery_time = 0.2
            joiner = Node(cfg, gen, priv_validator=None, db_backend="memdb")
            await joiner.start()
            await dial_all(joiner, nodes)
            target = nodes[0].block_store.height() + 2

            async def synced():
                while joiner.block_store.height() < target:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            assert joiner.block_store.base() == 1  # replayed from genesis
            assert not joiner.statesync_reactor.syncing
        finally:
            if joiner is not None and joiner.is_running:
                await joiner.stop()
            for n in nodes:
                if n.is_running:
                    await n.stop()

    async def test_malicious_chunk_server_banned_and_restore_survives(self, tmp_path):
        """Satellite: every validator serves a CORRUPT first chunk
        response.  The syncer must hash-reject it, ban the peer, refetch
        from another, and still complete the restore (peers reconnect as
        persistent dials are not used here, so two honest retries
        remain)."""
        nodes, pvs, gen = await make_serving_net(tmp_path, name="malnet")
        joiner = None
        corrupted = []
        try:
            for i in range(12):
                await nodes[0].mempool.check_tx(b"mal%d=%d" % (i, i))
            await wait_height(nodes, 7)

            # node2 always serves corrupted chunks
            evil = nodes[2].statesync_reactor
            orig_serve = evil._serve_chunk

            async def corrupt_serve(peer, msg):
                corrupted.append(msg["index"])
                from tendermint_tpu.statesync.reactor import CHUNK_CHANNEL, _enc

                await peer.send(
                    CHUNK_CHANNEL,
                    _enc("chunk_response", {
                        "height": msg["height"], "format": msg["format"],
                        "index": msg["index"], "chunk": b"\x66poison\x66",
                        "missing": False,
                    }),
                )

            evil._serve_chunk = corrupt_serve

            cfg = joiner_config(tmp_path, nodes, name="mal-joiner")
            joiner = Node(cfg, gen, priv_validator=None, db_backend="memdb")
            await joiner.start()
            # spy on the syncer's behaviour reports: the ban itself only
            # disconnects, and PEX may later re-dial the peer, so final
            # peer-set membership is not a stable signal
            reports = []
            orig_report = joiner.statesync_reactor.syncer.report_bad_peer

            async def spy_report(peer_id, reason):
                reports.append((peer_id, reason))
                await orig_report(peer_id, reason)

            joiner.statesync_reactor.syncer.report_bad_peer = spy_report
            await dial_all(joiner, nodes)
            target = nodes[0].block_store.height() + 2

            async def synced():
                while joiner.block_store.height() < target:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            assert joiner.block_store.base() > 1  # restore completed, no wedge
            if corrupted:
                # the corrupt peer served at least one chunk -> its bad
                # hash must have been caught and the peer reported/banned
                assert any(pid == nodes[2].node_key.id for pid, _ in reports), reports
        finally:
            if joiner is not None and joiner.is_running:
                await joiner.stop()
            for n in nodes:
                if n.is_running:
                    await n.stop()


class TestStatusPhase:
    async def test_solo_node_reports_caught_up(self, tmp_path):
        from tendermint_tpu.rpc.core import RPCCore

        pv = MockPV()
        gen = GenesisDoc(
            chain_id=CHAIN_ID,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        cfg = make_test_cfg(str(tmp_path / "solo"))
        cfg.rpc.laddr = ""
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        try:
            await node.start()

            async def reach(h):
                while node.block_store.height() < h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(1), 30.0)
            status = await RPCCore(node).status()
            assert status["sync_info"]["sync_phase"] == "caught_up"
            assert status["sync_info"]["catching_up"] is False
        finally:
            await node.stop()
