"""Fast-sync tests: deterministic scheduler/processor FSMs (the v2-style
table-testable tier, SURVEY.md §4 tier 5) + a real network catch-up.
"""

import asyncio

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.fastsync.processor import Processor, verify_commit_run
from tendermint_tpu.fastsync.scheduler import Scheduler
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tests.test_consensus_net import CHAIN_ID, make_net, stop_net, wait_all_height
from tests.test_types import make_block_id, make_commit, rand_validator_set

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))


class TestScheduler:
    def test_requests_spread_across_peers(self):
        s = Scheduler(initial_height=1, max_pending_per_peer=2)
        s.set_peer_range("p1", 1, 10)
        s.set_peer_range("p2", 1, 10)
        reqs = s.next_requests(now=0.0)
        for peer_id, h in reqs:
            s.mark_requested(peer_id, h, 0.0)
        assert len(reqs) == 4  # 2 per peer
        heights = sorted(h for _, h in reqs)
        assert heights == [1, 2, 3, 4]
        by_peer = {}
        for pid, h in reqs:
            by_peer.setdefault(pid, []).append(h)
        assert all(len(v) == 2 for v in by_peer.values())

    def test_received_and_processed_advance(self):
        s = Scheduler(1, max_pending_per_peer=10)
        s.set_peer_range("p1", 1, 3)
        for pid, h in s.next_requests(0.0):
            s.mark_requested(pid, h, 0.0)
        assert s.block_received("p1", 1)
        assert not s.block_received("p2", 1)  # wrong peer: unsolicited
        assert not s.block_received("p1", 9)  # never requested
        s.block_received("p1", 2)
        s.block_received("p1", 3)
        s.block_processed(1)
        s.block_processed(2)
        assert not s.is_caught_up()
        s.block_processed(3)
        assert s.is_caught_up()

    def test_remove_peer_reschedules(self):
        s = Scheduler(1)
        s.set_peer_range("p1", 1, 5)
        s.set_peer_range("p2", 1, 5)
        for pid, h in s.next_requests(0.0):
            s.mark_requested(pid, h, 0.0)
        freed = s.remove_peer("p1")
        # freed heights get re-requested from p2
        reqs = s.next_requests(0.0)
        re_requested = {h for _, h in reqs}
        assert set(freed) <= re_requested

    def test_timeout_reassigns(self):
        s = Scheduler(1, request_timeout=1.0)
        s.set_peer_range("p1", 1, 2)
        s.set_peer_range("p2", 1, 2)
        reqs = dict((h, pid) for pid, h in s.next_requests(0.0))
        for h, pid in reqs.items():
            s.mark_requested(pid, h, 0.0)
        # after the timeout everything is schedulable again
        reqs2 = s.next_requests(now=5.0)
        assert {h for _, h in reqs2} == set(reqs.keys())

    def test_peer_base_respected(self):
        s = Scheduler(1)
        s.set_peer_range("pruned", base=50, height=100)
        assert s.next_requests(0.0) == []  # peer pruned heights 1..49


class TestProcessor:
    def test_pairs_and_advance(self):
        from tendermint_tpu.types import Block, Header

        p = Processor(height=5)
        mk = lambda h: Block(Header(chain_id="c", height=h), [])
        p.add_block(6, mk(6), "p2")
        assert p.peek_two() is None
        p.add_block(5, mk(5), "p1")
        first, second = p.peek_two()
        assert first.height == 5 and second.height == 6
        p.pop_processed()
        assert p.height == 6

    def test_drop_invalid_reports_heights(self):
        from tendermint_tpu.types import Block, Header

        p = Processor(height=5)
        p.add_block(5, Block(Header(chain_id="c", height=5), []), "bad1")
        p.add_block(6, Block(Header(chain_id="c", height=6), []), "bad2")
        assert p.drop_invalid() == (5, 6)
        assert p.peek_two() is None


class TestVerifyCommitRun:
    def test_cross_height_batch(self):
        vset, pvs = rand_validator_set(6)
        pairs = []
        for h in (10, 11, 12):
            bid = make_block_id(bytes([h]))
            commit = make_commit(vset, pvs, h, 0, bid)
            pairs.append((bid, h, commit))
        assert verify_commit_run(vset, "test-chain", pairs) == [True, True, True]
        # tamper one height's commit: only that height fails
        bad_bid = make_block_id(b"\x63")
        bad = make_commit(vset, pvs, 13, 0, bad_bid)
        bad.signatures[2] = bad.signatures[2].__class__(
            bad.signatures[2].block_id_flag,
            bad.signatures[2].validator_address,
            bad.signatures[2].timestamp_ns,
            b"\x00" * 64,
        )
        pairs.append((bad_bid, 13, bad))
        assert verify_commit_run(vset, "test-chain", pairs) == [True, True, True, False]


class TestFastSyncNet:
    async def test_non_validator_fast_syncs(self, tmp_path):
        """3 validators progress; a non-validator full node joins with
        fast_sync on, downloads the chain, switches to consensus, and keeps
        following the head."""
        nodes, pvs = await make_net(tmp_path, 3, name="fs")
        try:
            await wait_all_height(nodes, 5)

            cfg = make_test_cfg(str(tmp_path / "syncer"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.base.fast_sync = True
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            gen = GenesisDoc(
                chain_id=CHAIN_ID,
                genesis_time_ns=1_700_000_000_000_000_000,
                validators=[
                    GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs
                ],
                consensus_params=_FAST_IOTA_PARAMS,
            )
            syncer = Node(cfg, gen, priv_validator=None, db_backend="memdb")
            await syncer.start()
            assert syncer.blockchain_reactor.fast_sync
            for n in nodes:
                addr = f"{n.node_key.id}@{n.switch.transport.listen_addr}"
                await syncer.switch.dial_peer(addr)

            # must catch up and then follow the moving head via consensus
            target = nodes[0].block_store.height() + 3

            async def synced():
                while True:
                    if syncer.block_store.height() >= target:
                        return
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            assert syncer.blockchain_reactor.blocks_synced > 0
            assert not syncer.blockchain_reactor.fast_sync  # switched over
            h = target - 1
            assert (
                syncer.block_store.load_block(h).hash()
                == nodes[0].block_store.load_block(h).hash()
            )
            await syncer.stop()
        finally:
            await stop_net(nodes)


class TestBehaviourReporting:
    """behaviour/reporter.go — reactors report conduct through a Reporter;
    MockReporter captures what was reported."""

    async def test_bad_block_response_reported(self):
        from tendermint_tpu.fastsync.reactor import BlockchainReactor
        from tendermint_tpu.p2p.behaviour import BAD_MESSAGE, MockReporter

        class _Peer:
            id = "peerX"

            async def send(self, *a):
                return True

        class _Store:
            def height(self):
                return 0

            def base(self):
                return 0

        reactor = BlockchainReactor.__new__(BlockchainReactor)
        reactor.reporter = MockReporter()
        reactor.fast_sync = True
        reactor.block_store = _Store()
        from tendermint_tpu.fastsync.reactor import BLOCKCHAIN_CHANNEL

        await reactor.receive(BLOCKCHAIN_CHANNEL, _Peer(), b"\x00garbage")
        reports = reactor.reporter.get("peerX")
        assert len(reports) == 1 and reports[0].kind == BAD_MESSAGE

    async def test_switch_reporter_stops_bad_and_marks_good(self):
        from tendermint_tpu.p2p.behaviour import (
            SwitchReporter,
            bad_message,
            consensus_vote,
        )

        stopped = []
        marked = []

        class _Book:
            def mark_good(self, pid):
                marked.append(pid)

        class _Switch:
            peers = {"p1": object(), "p2": object()}
            addr_book = _Book()

            async def stop_peer_for_error(self, peer, reason):
                stopped.append(reason)

        rep = SwitchReporter(_Switch())
        assert await rep.report(consensus_vote("p1"))
        assert marked == ["p1"]
        assert await rep.report(bad_message("p2", "bad"))
        assert stopped == ["bad"]
        assert not await rep.report(bad_message("ghost", "x"))  # unknown peer
