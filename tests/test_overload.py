"""Overload-robustness layer tests: admission control, backpressure and
priority QoS across the whole client path (token buckets, the mempool's
cheapest-first admission pipeline + priority eviction + rotated WAL, RPC
ingress caps with explicit overload errors, gossip frame pacing, and the
load generator against a live node)."""

import asyncio

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs.flowrate import TokenBucket
from tendermint_tpu.mempool import (
    Mempool,
    MempoolError,
    MempoolFullError,
    TxInCacheError,
    make_signed_tx,
    tx_priority,
)
from tendermint_tpu.rpc.jsonrpc import SERVER_OVERLOADED, RPCError


class _App:
    """Counting ABCI stub; per-tx priority override via `priorities`."""

    def __init__(self):
        self.calls = 0
        self.priorities = {}

    async def check_tx(self, req):
        self.calls += 1
        return abci.ResponseCheckTx(
            code=abci.CODE_TYPE_OK, priority=self.priorities.get(req.tx, 0)
        )


# ---------------------------------------------------------------------------
# TokenBucket (libs/flowrate.py)
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_allow_consumes_and_refills(self):
        b = TokenBucket(rate=10.0, burst=2.0, now=0.0)
        assert b.allow(now=0.0) and b.allow(now=0.0)
        assert not b.allow(now=0.0)  # burst exhausted
        assert b.retry_after(now=0.0) == pytest.approx(0.1)
        assert b.allow(now=0.15)  # 1.5 tokens refilled
        assert not b.allow(now=0.15)

    def test_refill_caps_at_burst(self):
        b = TokenBucket(rate=100.0, burst=3.0, now=0.0)
        for _ in range(3):
            assert b.allow(now=100.0)
        assert not b.allow(now=100.0)

    def test_rejected_allow_leaves_bucket_untouched(self):
        b = TokenBucket(rate=1.0, burst=1.0, now=0.0)
        assert b.allow(now=0.0)
        for _ in range(5):
            assert not b.allow(now=0.5)  # half a token; unchanged by asks
        assert b.allow(now=1.0)

    def test_debit_paces_oversized_frames(self):
        # a frame larger than the burst must spread out, not starve
        b = TokenBucket(rate=100.0, burst=50.0, now=0.0)
        assert b.debit(250.0, now=0.0) == pytest.approx(2.0)
        assert b.debit(100.0, now=2.0) == pytest.approx(1.0)

    def test_retry_after_caps_ask_at_burst(self):
        b = TokenBucket(rate=1.0, burst=2.0, now=0.0)
        b.allow(2.0, now=0.0)
        # an over-burst ask is priced as a full burst, never "infinite"
        assert b.retry_after(10.0, now=0.0) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# Fee-declared priority (mempool.tx_priority)
# ---------------------------------------------------------------------------


class TestTxPriority:
    def test_plain_and_enveloped_fee_prefix(self):
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        assert tx_priority(b"fee:42:k=v") == 42
        k = Ed25519PrivKey.from_secret(b"prio")
        assert tx_priority(make_signed_tx(k, b"fee:7:k=v")) == 7
        assert tx_priority(make_signed_tx(k, b"k=v")) == 0

    def test_malformed_or_absent_fee_is_zero(self):
        assert tx_priority(b"k=v") == 0
        assert tx_priority(b"fee:k=v") == 0
        assert tx_priority(b"fee::k=v") == 0
        assert tx_priority(b"fee:12a:k=v") == 0
        # bounded digit run: no attacker-sized big-int parse
        assert tx_priority(b"fee:" + b"9" * 40 + b":k=v") == 0


# ---------------------------------------------------------------------------
# Priority mempool: reap order + eviction
# ---------------------------------------------------------------------------


class TestMempoolPriority:
    async def test_reap_drains_highest_priority_first(self):
        app = _App()
        mp = Mempool(app, {})
        await mp.check_tx(b"fee:1:a=1")
        await mp.check_tx(b"b=2")  # priority 0
        await mp.check_tx(b"fee:5:c=3")
        await mp.check_tx(b"fee:1:d=4")
        reaped = mp.reap_max_bytes_max_gas(-1, -1)
        # priority desc, arrival seq within a priority class
        assert reaped == [b"fee:5:c=3", b"fee:1:a=1", b"fee:1:d=4", b"b=2"]

    async def test_app_priority_overrides_fee(self):
        app = _App()
        app.priorities[b"vip=1"] = 9
        mp = Mempool(app, {})
        await mp.check_tx(b"fee:5:a=1")
        await mp.check_tx(b"vip=1")
        assert mp.reap_max_bytes_max_gas(-1, -1)[0] == b"vip=1"

    async def test_full_pool_evicts_lowest_priority_newest_first(self):
        app = _App()
        mp = Mempool(app, {"size": 3})
        await mp.check_tx(b"fee:1:a=1")
        await mp.check_tx(b"fee:2:b=2")
        await mp.check_tx(b"fee:1:c=3")
        # a better-paying tx displaces the NEWEST of the lowest class
        await mp.check_tx(b"fee:5:d=4")
        assert mp.size() == 3
        txs = set(mp.reap_max_bytes_max_gas(-1, -1))
        assert b"fee:5:d=4" in txs and b"fee:1:c=3" not in txs
        assert b"fee:1:a=1" in txs  # older equal-priority tx kept its place
        assert mp.txs_bytes == sum(len(t) for t in txs)

    async def test_full_pool_rejects_non_displacing_tx_explicitly(self):
        app = _App()
        mp = Mempool(app, {"size": 2})
        await mp.check_tx(b"fee:3:a=1")
        await mp.check_tx(b"fee:3:b=2")
        with pytest.raises(MempoolFullError):
            await mp.check_tx(b"fee:3:c=3")  # equal priority displaces nothing
        # the rejection was state-dependent: the bytes are NOT poisoned in
        # the cache and no app round-trip was bought
        assert app.calls == 2
        with pytest.raises(MempoolFullError):
            await mp.check_tx(b"fee:3:c=3")  # not TxInCacheError

    async def test_failed_eviction_evicts_nothing(self):
        """A rejection must never ALSO drop valid txs: when the evictable
        lower-priority set cannot free enough bytes, _make_room raises
        with the pool untouched (review regression: the one-victim-at-a-
        time loop used to evict, THEN discover it wasn't enough)."""
        app = _App()
        mp = Mempool(app, {"size": 100, "max_txs_bytes": 250})
        await mp.check_tx(b"fee:1:" + b"a" * 94)  # 100 bytes, priority 1
        await mp.check_tx(b"fee:3:" + b"b" * 94)  # 100 bytes, priority 3
        with pytest.raises(MempoolFullError):
            # needs 200 bytes freed; only the 100-byte prio-1 tx is below
            # priority 2 — insufficient, so NOTHING may be evicted
            mp._make_room(200, 2)
        assert mp.size() == 2 and mp.txs_bytes == 200

    async def test_evicted_tx_can_re_enter(self):
        app = _App()
        mp = Mempool(app, {"size": 1})
        await mp.check_tx(b"fee:1:a=1")
        await mp.check_tx(b"fee:5:b=2")  # evicts a=1
        assert mp.reap_max_bytes_max_gas(-1, -1) == [b"fee:5:b=2"]
        # eviction cleared the cache entry: the victim is a fresh tx again
        with pytest.raises(MempoolFullError):
            await mp.check_tx(b"fee:1:a=1")


# ---------------------------------------------------------------------------
# Concurrent admission pipeline (the satellite coverage task): one engine
# flush for the valid set, zero verifies for pre-rejected garbage,
# deterministic priority order in the subsequent reap
# ---------------------------------------------------------------------------


class TestConcurrentAdmission:
    async def test_mixed_burst_from_many_senders(self):
        from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
        from tendermint_tpu.crypto.keys import Ed25519PrivKey
        from tendermint_tpu.mempool import SIGNED_TX_PREFIX

        class _CountingVerifier(BatchVerifier):
            def __init__(self):
                super().__init__(min_device_batch=10**9)
                self.calls = []

            def start_warmup(self):
                return self

            def verify(self, pubkeys, msgs, sigs):
                self.calls.append(len(sigs))
                return super().verify(pubkeys, msgs, sigs)

        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            app = _App()
            mp = Mempool(app, {"sig_precheck": True, "max_tx_bytes": 4096})
            mp.sig_verifier = svc
            keys = [Ed25519PrivKey.from_secret(b"adm%d" % i) for i in range(16)]
            valid = [
                make_signed_tx(k, b"fee:%d:adm%d=v" % (5 if i < 8 else 1, i))
                for i, k in enumerate(keys)
            ]
            garbage = [SIGNED_TX_PREFIX + b"\x01" * (10 + i) for i in range(8)]
            dups = list(valid[:8])
            oversized = [b"o%d=" % i + b"x" * 4096 for i in range(4)]

            async def send(tx, i):
                try:
                    await mp.check_tx(tx, sender=f"s{i % 4}")
                    return "ok"
                except TxInCacheError:
                    return "dup"
                except MempoolError as e:
                    return str(e)

            # valid txs first in the task list so the dup copies always
            # lose the cache race deterministically
            results = await asyncio.gather(
                *(send(tx, i) for i, tx in enumerate(valid)),
                *(send(tx, i) for i, tx in enumerate(garbage)),
                *(send(tx, i) for i, tx in enumerate(dups)),
                *(send(tx, i) for i, tx in enumerate(oversized)),
            )
            ok = results[:16]
            garb = results[16:24]
            dup = results[24:32]
            over = results[32:]
            assert ok == ["ok"] * 16
            assert all("envelope" in r for r in garb)
            assert dup == ["dup"] * 8
            assert all("too large" in r for r in over)
            # EXACTLY one engine flush, and it carried only the valid set:
            # malformed envelopes, duplicates and oversized txs were all
            # rejected before any signature work
            assert cv.calls == [16], cv.calls
            assert app.calls == 16
            # deterministic priority-ordered reap: the fee:5 class (arrival
            # order within it), then the fee:1 class
            assert mp.reap_max_bytes_max_gas(-1, -1) == valid[:8] + valid[8:]
            # the duplicate copies recorded their senders on the originals
            assert all(mtx.senders for mtx in list(mp.txs.values())[:8])
        finally:
            await svc.stop()


# ---------------------------------------------------------------------------
# Mempool WAL rotation (satellite): flood past the cap, assert rotation +
# bounded total + replay
# ---------------------------------------------------------------------------


class TestMempoolWalRotation:
    async def test_flood_rotates_and_replays(self, tmp_path):
        import os

        app = _App()
        mp = Mempool(app, {"size": 10_000})
        limit = 8192
        mp.init_wal(str(tmp_path / "mwal"), size_limit=limit)
        txs = [b"wal%04d=" % i + b"v" * 80 for i in range(120)]
        try:
            for tx in txs:
                await mp.check_tx(tx)
        finally:
            wal_dir = str(tmp_path / "mwal")
            replayed = mp.wal_txs()
            mp.close_wal()
        names = sorted(os.listdir(wal_dir))
        assert "wal" in names
        assert any(n.startswith("wal.") for n in names), (
            f"flood never rotated the journal: {names}"
        )
        total = sum(os.path.getsize(os.path.join(wal_dir, n)) for n in names)
        assert total <= limit, f"journal {total} bytes exceeds cap {limit}"
        # replay yields a clean SUFFIX of the accepted stream (oldest
        # chunks were dropped by the cap), every entry decodable
        assert replayed, "replay returned nothing"
        assert replayed == txs[len(txs) - len(replayed):]

    async def test_replay_survives_torn_tail(self, tmp_path):
        app = _App()
        mp = Mempool(app, {})
        mp.init_wal(str(tmp_path / "mwal"))
        await mp.check_tx(b"a=1")
        await mp.check_tx(b"b=2")
        mp._wal.write(b"deadbee")  # torn write: odd-length hex, no newline
        mp._wal.flush()
        assert mp.wal_txs() == [b"a=1", b"b=2"]
        mp.close_wal()


# ---------------------------------------------------------------------------
# Gossip frame policy (mempool_reactor.chunk_txs)
# ---------------------------------------------------------------------------


class TestChunkTxs:
    def test_frames_respect_byte_cap(self):
        from tendermint_tpu.mempool_reactor import chunk_txs

        txs = [b"x" * 40 for _ in range(10)]
        frames = chunk_txs(txs, 100)
        assert [len(f) for f in frames] == [2, 2, 2, 2, 2]
        assert [tx for f in frames for tx in f] == txs

    def test_oversized_tx_rides_alone(self):
        from tendermint_tpu.mempool_reactor import chunk_txs

        frames = chunk_txs([b"a" * 10, b"b" * 500, b"c" * 10], 100)
        assert frames == [[b"a" * 10], [b"b" * 500], [b"c" * 10]]
        assert chunk_txs([], 100) == []


# ---------------------------------------------------------------------------
# RPC ingress admission control (RPCCore against a fake node)
# ---------------------------------------------------------------------------


class _FakeNode:
    def __init__(self, mempool, event_bus=None):
        self.mempool = mempool
        self.event_bus = event_bus


class _OkMempool:
    async def check_tx(self, tx, sender=""):
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)


class _GateMempool:
    """check_tx blocks until released — models a stalled ingress path."""

    def __init__(self):
        self.release = asyncio.Event()
        self.entered = 0

    async def check_tx(self, tx, sender=""):
        self.entered += 1
        await self.release.wait()
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)


def _make_core(**kw):
    from tendermint_tpu.rpc.core import RPCCore

    node = kw.pop("node", None) or _FakeNode(_OkMempool())
    return RPCCore(node, **kw)


class TestRPCAdmission:
    async def test_per_source_rate_limit_with_retry_after(self):
        core = _make_core(broadcast_rate=1000.0, broadcast_rate_burst=2)
        for _ in range(2):
            await core.call("broadcast_tx_sync", {"tx": b"a=1"}, source="1.2.3.4")
        with pytest.raises(RPCError) as ei:
            await core.call("broadcast_tx_sync", {"tx": b"a=1"}, source="1.2.3.4")
        assert ei.value.code == SERVER_OVERLOADED
        # data is a real JSON object, not a doubly-encoded string
        assert ei.value.data["retry_after"] >= 0
        # a different source has its own bucket; in-proc (no source) is trusted
        await core.call("broadcast_tx_sync", {"tx": b"a=1"}, source="5.6.7.8")
        await core.call("broadcast_tx_sync", {"tx": b"a=1"})

    async def test_source_bucket_table_is_lru_bounded(self):
        core = _make_core(broadcast_rate=1000.0, broadcast_rate_burst=5)
        core.MAX_SOURCES = 8
        for i in range(50):
            await core.call("broadcast_tx_sync", {"tx": b"a=1"}, source=f"10.0.0.{i}")
        assert len(core._buckets) <= 8

    async def test_inflight_cap_rejects_instead_of_queueing(self):
        gate = _GateMempool()
        core = _make_core(node=_FakeNode(gate), max_broadcast_inflight=1)
        first = asyncio.ensure_future(
            core.call("broadcast_tx_sync", {"tx": b"a=1"}, source="s")
        )
        while gate.entered == 0:
            await asyncio.sleep(0)
        with pytest.raises(RPCError) as ei:
            await core.call("broadcast_tx_sync", {"tx": b"b=2"}, source="s")
        assert ei.value.code == SERVER_OVERLOADED
        gate.release.set()
        await first
        # slot released: admitted again
        await core.call("broadcast_tx_sync", {"tx": b"c=3"}, source="s")
        assert core._inflight == 0

    async def test_async_broadcast_is_bounded_and_releases(self):
        gate = _GateMempool()
        core = _make_core(node=_FakeNode(gate), max_broadcast_inflight=2)
        await core.call("broadcast_tx_async", {"tx": b"a=1"})
        await core.call("broadcast_tx_async", {"tx": b"b=2"})
        with pytest.raises(RPCError) as ei:
            await core.call("broadcast_tx_async", {"tx": b"c=3"})
        assert ei.value.code == SERVER_OVERLOADED
        gate.release.set()
        while core._inflight:
            await asyncio.sleep(0)
        await core.call("broadcast_tx_async", {"tx": b"d=4"})
        while core._inflight:
            await asyncio.sleep(0)

    async def test_mempool_full_maps_to_explicit_overload(self):
        class _FullMempool:
            async def check_tx(self, tx, sender=""):
                raise MempoolFullError(100, 10_000)

        core = _make_core(node=_FakeNode(_FullMempool()))
        with pytest.raises(RPCError) as ei:
            await core.call("broadcast_tx_sync", {"tx": b"a=1"}, source="s")
        assert ei.value.code == SERVER_OVERLOADED
        assert "retry_after" in (ei.value.data or "")

    async def test_commit_waiter_cap_and_no_subscription_leak(self):
        """The satellite: N parallel commit-waits during a stall — excess
        waiters get the overload error immediately, admitted ones time
        out, and NO event-bus subscription survives."""
        from tendermint_tpu.types.events import EventBus

        bus = EventBus()
        await bus.start()
        try:
            core = _make_core(
                node=_FakeNode(_OkMempool(), event_bus=bus),
                max_commit_waiters=4,
                timeout_broadcast_tx_commit=0.2,
            )
            results = await asyncio.gather(
                *(
                    core.call("broadcast_tx_commit", {"tx": b"ctx%d=1" % i}, source="s")
                    for i in range(10)
                ),
                return_exceptions=True,
            )
            overloaded = [
                r for r in results
                if isinstance(r, RPCError) and r.code == SERVER_OVERLOADED
            ]
            timed_out = [
                r for r in results
                if isinstance(r, RPCError) and "timed out" in r.message
            ]
            assert len(overloaded) == 6 and len(timed_out) == 4, results
            assert core._commit_waiters == 0
            assert not bus.pubsub._subs, "leaked event-bus subscriptions"
        finally:
            await bus.stop()


# ---------------------------------------------------------------------------
# RPC server body/batch bounds (live node; the small-fix satellite)
# ---------------------------------------------------------------------------


async def _make_live_node(tmp_path, mutate_cfg=None):
    from tendermint_tpu.config import test_config as make_test_cfg
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
    from tendermint_tpu.types.params import BlockParams, ConsensusParams

    pv = MockPV()
    gen = GenesisDoc(
        chain_id="overload-chain",
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
        consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
    )
    cfg = make_test_cfg(str(tmp_path / "overload"))
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    if mutate_cfg:
        mutate_cfg(cfg)
    node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
    await node.start()
    while node.block_store.height() < 1:
        await asyncio.sleep(0.02)
    return node


class TestRPCServerBounds:
    async def test_oversized_and_malformed_bodies_rejected_cleanly(self, tmp_path):
        import aiohttp

        def small_body(cfg):
            cfg.rpc.max_body_bytes = 1000
            cfg.rpc.max_batch_request_items = 5

        node = await _make_live_node(tmp_path, small_body)
        try:
            base = f"http://{node.rpc_server.listen_addr}"
            async with aiohttp.ClientSession() as s:
                # oversized body: bounded read + explicit JSON-RPC error,
                # never an unbounded json.loads
                async with s.post(base, data=b"x" * 5000) as r:
                    d = await r.json()
                assert d["error"]["code"] == -32600
                assert "exceeds 1000 bytes" in d["error"]["message"]
                # non-JSON body under the cap: parse error
                async with s.post(base, data=b"\xff\xfenot json") as r:
                    d = await r.json()
                assert d["error"]["code"] == -32700
                # batch fan-out cap
                reqs = [
                    {"jsonrpc": "2.0", "id": i, "method": "health", "params": {}}
                    for i in range(6)
                ]
                async with s.post(base, json=reqs) as r:
                    d = await r.json()
                assert d["error"]["code"] == -32600
                # a well-formed request still works on the same server
                async with s.get(f"{base}/health") as r:
                    assert "result" in await r.json()
        finally:
            await node.stop()

    async def test_live_rate_limit_and_loadgen_roundtrip(self, tmp_path):
        """End-to-end: a live node with a per-source rate limit throttles
        the load generator with explicit retry_after errors while still
        accepting the admitted stream and committing blocks."""
        from tendermint_tpu.tools import loadgen

        def qos(cfg):
            cfg.rpc.broadcast_rate = 30.0
            cfg.rpc.broadcast_rate_burst = 10
            cfg.mempool.sig_precheck = True

        node = await _make_live_node(tmp_path, qos)
        try:
            result = await loadgen.run_load(
                [node.rpc_server.listen_addr],
                duration=1.5,
                rate=0.0,
                connections=2,
                tx_bytes=96,
                mode="sync",
                fee=2,
            )
            assert result["accepted"] > 0
            assert result["throttled"] > 0
            assert result["retry_after_seen"] == result["throttled"]
            assert result["transport_errors"] == 0
            assert result["tx_ingress_sustained_tps"] > 0
            assert result["commits_under_load"] >= 1
        finally:
            await node.stop()
