"""Tooling tests: tm-signer-harness, OpenAPI spec, localnet process harness.

Reference parity: tools/tm-signer-harness/internal/test_harness.go,
rpc/swagger/swagger.yaml, networks/local/.
"""

import asyncio
import sys

import pytest

from tendermint_tpu.privval import FilePV, SignerServer
from tendermint_tpu.tools.signer_harness import run_harness


class TestSignerHarness:
    async def test_good_signer_passes_all_checks(self, tmp_path):
        pv = FilePV.load_or_generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
        laddr = "tcp://127.0.0.1:31717"
        harness_task = asyncio.ensure_future(run_harness(laddr, accept_timeout=10.0))
        await asyncio.sleep(0.1)
        signer = SignerServer(laddr, pv, retries=40, retry_interval=0.25)
        await signer.start()
        try:
            results = await asyncio.wait_for(harness_task, 30.0)
            assert [c for c, ok, _ in results if ok] == [
                "PubKey",
                "SignProposal",
                "SignVote",
                "DoubleSign",
            ]
        finally:
            await signer.stop()

    async def test_expected_pubkey_mismatch_fails(self, tmp_path):
        from tendermint_tpu.tools.signer_harness import HarnessFailure

        pv = FilePV.load_or_generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
        laddr = "tcp://127.0.0.1:31718"
        harness_task = asyncio.ensure_future(
            run_harness(laddr, accept_timeout=10.0, expected_pubkey_hex="ab" * 32)
        )
        await asyncio.sleep(0.1)
        signer = SignerServer(laddr, pv, retries=40, retry_interval=0.25)
        await signer.start()
        try:
            with pytest.raises(HarnessFailure):
                await asyncio.wait_for(harness_task, 30.0)
        finally:
            await signer.stop()


class TestOpenAPI:
    def test_spec_covers_every_route(self):
        from tendermint_tpu.rpc.core import RPCCore
        from tendermint_tpu.rpc.openapi import generate_spec

        spec = generate_spec("test")
        assert spec["openapi"].startswith("3.")
        for route in RPCCore.ROUTES:
            assert f"/{route}" in spec["paths"], route
        # parameter typing came from annotations
        p = {x["name"]: x for x in spec["paths"]["/abci_query"]["get"]["parameters"]}
        assert p["height"]["schema"]["type"] == "integer"
        assert p["prove"]["schema"]["type"] == "boolean"
        assert "bytes" in p["data"]["schema"].get("description", "")
        # unsafe routes tagged
        assert spec["paths"]["/unsafe_dump_tasks"]["get"]["tags"] == ["unsafe"]

    async def test_served_by_rpc(self, tmp_path):
        from tests.test_rpc import make_rpc_node  # reuse the live-node helper

        node = await make_rpc_node(tmp_path)
        try:
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{node.rpc_server.listen_addr}/openapi.json"
                ) as r:
                    assert r.status == 200
                    spec = await r.json()
                    assert "/status" in spec["paths"]
        finally:
            await node.stop()


def _free_base_port(n_nodes: int) -> int:
    """A base port whose testnet-derived range (base+10i p2p, +1 rpc) is
    currently free — fixed ports collide when suites run in parallel."""
    import os
    import socket

    for _ in range(20):
        base = int.from_bytes(os.urandom(2), "big") % 30000 + 20000
        socks = []
        try:
            for i in range(n_nodes):
                for d in (0, 1):
                    s = socket.socket()
                    socks.append(s)  # before bind: close it even on failure
                    s.bind(("127.0.0.1", base + 10 * i + d))
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port range found")


class TestLocalnetHarness:
    async def test_two_node_localnet_processes(self, tmp_path):
        """networks/local/run_localnet.py against a generated testnet —
        real OS processes, real TCP, real configs (BASELINE config #1 rig,
        shrunk to 2 validators for suite time).  Dynamic ports; the
        harness itself gates on every node's RPC reporting height >= 1
        before the duration clock starts, so per-process JAX cold start
        under suite load cannot eat the measurement window."""
        import json as _json
        import subprocess

        build = str(tmp_path / "build")
        gen = subprocess.run(
            [
                sys.executable, "-m", "tendermint_tpu.cli", "testnet",
                "--validators", "2", "--output", build,
                "--base-port", str(_free_base_port(2)), "--fast",
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert gen.returncode == 0, gen.stderr
        run = subprocess.run(
            [
                sys.executable, "networks/local/run_localnet.py", build,
                "--duration", "6", "--startup-timeout", "120", "--json",
            ],
            capture_output=True,
            text=True,
            timeout=200,
            cwd="/root/repo",
        )
        assert run.returncode == 0, f"stdout={run.stdout}\nstderr={run.stderr}"
        assert "localnet healthy" in run.stdout
        result = _json.loads(run.stdout.strip().splitlines()[-1])
        assert result["blocks"] >= 3
        assert result["commits_per_sec"] > 0
