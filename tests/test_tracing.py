"""Flight recorder (libs/tracing.py): ring semantics, overhead budget,
span-chain analysis, the dump_flight_recorder RPC route and the
verify-engine event stream."""

import time

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.tracing import FlightRecorder, NopRecorder


class TestRing:
    def test_wraps_and_keeps_last_size_events(self):
        r = FlightRecorder(size=8)
        for i in range(20):
            r.record("step", height=i)
        evs = r.events()
        assert len(evs) == 8
        assert [e["height"] for e in evs] == list(range(12, 20))
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)

    def test_t_ns_monotonic(self):
        r = FlightRecorder(size=64)
        for i in range(32):
            r.record("step", height=i)
        ts = [e["t_ns"] for e in r.events()]
        assert all(a <= b for a, b in zip(ts, ts[1:]))

    def test_since_watermark(self):
        r = FlightRecorder(size=64)
        for i in range(10):
            r.record("step", height=i)
        snap = r.snapshot()
        assert snap["next_seq"] == 10 and snap["dropped"] == 0
        r.record("step", height=10)
        fresh = r.events(since=snap["next_seq"])
        assert [e["height"] for e in fresh] == [10]

    def test_disabled_and_nop_record_nothing(self):
        for r in (FlightRecorder(size=8, enabled=False), NopRecorder()):
            r.record("step", height=1)
            assert r.events() == []
            assert r.snapshot()["enabled"] is False

    def test_record_overhead_budget(self):
        # contract: < 1 us/event enabled; tripwire at 5 us so CI-host
        # noise can't flake the suite while a 10x regression still fails
        r = FlightRecorder(size=4096)
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            r.record("verify.flush", batch=4, wait_ms=0.2, quantum_ms=0.2)
        per_event = (time.perf_counter() - t0) / n
        assert per_event < 5e-6, f"record() took {per_event * 1e6:.2f} us/event"


class TestSpanChains:
    def _chain_events(self, heights, skip=()):
        r = FlightRecorder(size=1024)
        for h in heights:
            for step in ("NewHeight", "NewRound", *tracing.REQUIRED_STEPS):
                if (h, step) not in skip:
                    r.record("step", height=h, round=0, step=step)
        return r.events()

    def test_step_chains_and_complete_heights(self):
        evs = self._chain_events([5, 6, 7], skip={(6, "Precommit")})
        chains = tracing.step_chains(evs)
        assert set(chains) == {5, 6, 7}
        assert tracing.complete_heights(chains) == [5, 7]

    def test_block_breakdown_medians(self):
        evs = self._chain_events([1, 2, 3, 4])
        bd = tracing.block_breakdown(evs)
        assert bd is not None
        assert bd["source"] == "flight_recorder"
        assert bd["blocks"] == 3  # heights 1-3 have a next-height Propose
        for k in ("propose_ms", "prevote_ms", "precommit_ms", "commit_ms", "block_ms"):
            assert bd[k] >= 0

    def test_block_breakdown_needs_consecutive_chains(self):
        assert tracing.block_breakdown(self._chain_events([3])) is None
        assert tracing.block_breakdown([]) is None


class TestRPCRoute:
    async def test_dump_flight_recorder_route(self):
        from tendermint_tpu.rpc.core import RPCCore

        class _StubNode:
            flight_recorder = FlightRecorder(size=32)

        node = _StubNode()
        node.flight_recorder.record("step", height=1, round=0, step="Propose")
        core = RPCCore(node)
        snap = await core.call("dump_flight_recorder")
        assert snap["enabled"] is True
        assert snap["events"][0]["kind"] == "step"
        assert snap["events"][0]["height"] == 1
        # seq watermark polling: nothing new -> empty
        again = await core.call("dump_flight_recorder", {"since": snap["next_seq"]})
        assert again["events"] == []

    async def test_route_survives_node_without_recorder(self):
        from tendermint_tpu.rpc.core import RPCCore

        snap = await RPCCore(object()).call("dump_flight_recorder")
        assert snap == {
            "enabled": False, "size": 0, "next_seq": 0, "dropped": 0, "events": [],
        }


class TestVerifyEngineEvents:
    async def test_async_batcher_emits_enqueue_and_flush_spans(self):
        from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        rec = FlightRecorder(size=256)
        # min_device_batch above any test batch: the host path serves, no
        # device compile — this test is about the event stream, not JAX
        bv = BatchVerifier(min_device_batch=1 << 30, recorder=rec)
        svc = AsyncBatchVerifier(bv)
        await svc.start()
        try:
            k = Ed25519PrivKey.from_secret(b"trace")
            msg = b"\x08\x02\x11" + bytes(40)
            assert await svc.verify_one(k.pub_key().bytes(), msg, k.sign(msg))
        finally:
            await svc.stop()
        kinds = [e["kind"] for e in rec.events()]
        assert "verify.enqueue" in kinds
        assert "verify.flush" in kinds
        assert "verify.dispatch" in kinds
        flush = next(e for e in rec.events() if e["kind"] == "verify.flush")
        assert flush["batch"] >= 1 and flush["wait_ms"] >= 0
        dispatch = next(e for e in rec.events() if e["kind"] == "verify.dispatch")
        assert dispatch["path"] == "host" and dispatch["n"] >= 1
