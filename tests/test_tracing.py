"""Flight recorder (libs/tracing.py): ring semantics, overhead budget,
span-chain analysis, the dump_flight_recorder RPC route and the
verify-engine event stream."""

import os
import time

from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.tracing import FlightRecorder, NopRecorder


class TestRing:
    def test_wraps_and_keeps_last_size_events(self):
        r = FlightRecorder(size=8)
        for i in range(20):
            r.record("step", height=i)
        evs = r.events()
        assert len(evs) == 8
        assert [e["height"] for e in evs] == list(range(12, 20))
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)

    def test_t_ns_monotonic(self):
        r = FlightRecorder(size=64)
        for i in range(32):
            r.record("step", height=i)
        ts = [e["t_ns"] for e in r.events()]
        assert all(a <= b for a, b in zip(ts, ts[1:]))

    def test_since_watermark(self):
        r = FlightRecorder(size=64)
        for i in range(10):
            r.record("step", height=i)
        snap = r.snapshot()
        assert snap["next_seq"] == 10 and snap["dropped"] == 0
        r.record("step", height=10)
        fresh = r.events(since=snap["next_seq"])
        assert [e["height"] for e in fresh] == [10]

    def test_disabled_and_nop_record_nothing(self):
        for r in (FlightRecorder(size=8, enabled=False), NopRecorder()):
            r.record("step", height=1)
            assert r.events() == []
            assert r.snapshot()["enabled"] is False

    def test_record_overhead_budget(self):
        # contract: < 1 us/event enabled; tripwire at 5 us so CI-host
        # noise can't flake the suite while a 10x regression still fails
        r = FlightRecorder(size=4096)
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            r.record("verify.flush", batch=4, wait_ms=0.2, quantum_ms=0.2)
        per_event = (time.perf_counter() - t0) / n
        assert per_event < 5e-6, f"record() took {per_event * 1e6:.2f} us/event"


class TestSampling:
    def test_one_in_n_with_factor_recorded(self):
        r = FlightRecorder(size=256, sample_high_rate=4)
        for _ in range(16):
            r.record_sampled("gossip.wakeup", peer="ab")
        evs = r.events()
        assert len(evs) == 4  # 1-in-4
        assert all(e["sampled"] == 4 for e in evs)
        # consumers re-scale by the recorded factor
        assert sum(e["sampled"] for e in evs) == 16

    def test_default_factor_preserves_record_everything(self):
        r = FlightRecorder(size=256)  # sample_high_rate=1, the small-net default
        for _ in range(10):
            r.record_sampled("gossip.wakeup", peer="ab")
        evs = r.events()
        assert len(evs) == 10
        assert all("sampled" not in e for e in evs)

    def test_counters_are_per_kind_and_low_rate_kinds_unaffected(self):
        r = FlightRecorder(size=256, sample_high_rate=8)
        for i in range(8):
            r.record_sampled("gossip.wakeup", peer="ab")
            r.record("commit", height=i)  # plain record never sampled
        kinds = [e["kind"] for e in r.events()]
        assert kinds.count("gossip.wakeup") == 1
        assert kinds.count("commit") == 8

    def test_disabled_recorder_samples_nothing(self):
        r = FlightRecorder(size=8, enabled=False, sample_high_rate=4)
        r.record_sampled("gossip.wakeup")
        assert r.events() == []
        NopRecorder().record_sampled("gossip.wakeup")  # must not raise

    def test_factor_must_be_positive(self):
        import pytest

        with pytest.raises(ValueError):
            FlightRecorder(size=8, sample_high_rate=0)


class TestKindsFilterAndAnchor:
    def test_events_kinds_prefix_filter(self):
        r = FlightRecorder(size=64)
        r.record("step", height=1, step="Propose")
        r.record("gossip.wakeup", peer="ab")
        r.record("gossip.votes", n=2)
        r.record("verify.flush", batch=2)
        assert [e["kind"] for e in r.events(kinds=["gossip."])] == [
            "gossip.wakeup", "gossip.votes",
        ]
        assert [e["kind"] for e in r.events(kinds=["step", "verify."])] == [
            "step", "verify.flush",
        ]
        snap = r.snapshot(kinds=["step"])
        assert [e["kind"] for e in snap["events"]] == ["step"]
        assert snap["next_seq"] == 4  # watermark unaffected by the filter

    def test_anchor_present_and_resampled_on_snapshot(self):
        r = FlightRecorder(size=8)
        a1 = r.snapshot()["anchor"]
        assert a1["mono_ns"] >= r.anchor_mono_ns
        assert set(a1) == {"mono_ns", "wall_ns"}
        time.sleep(0.002)
        a2 = r.snapshot()["anchor"]
        # re-sampled at dump time, not the construction-time anchor
        assert a2["mono_ns"] > a1["mono_ns"]

    def test_anchor_wall_fn_pluggable_via_skewed_clock(self):
        from tendermint_tpu.chaos.clock import SkewedClock

        clock = SkewedClock(3.0)
        r = FlightRecorder(size=8, wall_ns_fn=clock.time_ns)
        a = r.snapshot()["anchor"]
        assert abs(a["wall_ns"] - 3_000_000_000 - time.time_ns()) < 1_000_000_000


class TestSpanReport:
    def _events(self, spec):
        """spec: {height: [steps]} recorded in height order."""
        r = FlightRecorder(size=1024)
        for h in sorted(spec):
            for step in spec[h]:
                r.record("step", height=h, round=0, step=step)
        return r.events()

    def test_complete_interior_heights(self):
        evs = self._events({h: list(tracing.REQUIRED_STEPS) for h in (1, 2, 3, 4)})
        rep = tracing.span_report(evs)
        assert rep["complete"] == [2, 3]
        assert rep["truncated"] == [] and rep["bad"] == {}
        assert rep["interior"] == 2

    def test_prefix_hole_is_truncated_when_ring_wrapped(self):
        # height 3 lost its Propose+Prevote to eviction: with dropped>0
        # that is honest ring wrap (oldest-first), NOT a failure — the
        # fix for `trace --check` being useless on busy nets
        spec = {h: list(tracing.REQUIRED_STEPS) for h in (1, 2, 4, 5)}
        spec[3] = list(tracing.REQUIRED_STEPS[2:])
        evs = self._events(spec)
        rep = tracing.span_report(evs, dropped=17)
        assert rep["truncated"] == [3]
        assert rep["bad"] == {}
        assert rep["complete"] == [2, 4]
        # a `since` watermark truncates the same way (dump streamed fresh)
        rep = tracing.span_report(evs, since=5)
        assert rep["truncated"] == [3] and rep["bad"] == {}

    def test_prefix_hole_without_wrap_is_a_failure(self):
        spec = {h: list(tracing.REQUIRED_STEPS) for h in (1, 2, 4)}
        spec[3] = list(tracing.REQUIRED_STEPS[1:])
        rep = tracing.span_report(self._events(spec), dropped=0)
        assert rep["bad"] == {3: [tracing.REQUIRED_STEPS[0]]}
        assert rep["truncated"] == []

    def test_mid_chain_hole_is_a_failure_even_wrapped(self):
        # a LATER step present while an earlier one is missing cannot be
        # oldest-first eviction — real instrumentation/consensus bug
        spec = {h: list(tracing.REQUIRED_STEPS) for h in (1, 2, 4)}
        spec[3] = [s for s in tracing.REQUIRED_STEPS if s != "Precommit"]
        rep = tracing.span_report(self._events(spec), dropped=999)
        assert rep["bad"] == {3: ["Precommit"]}

    def test_edge_heights_excluded(self):
        evs = self._events({1: ["Commit"], 2: list(tracing.REQUIRED_STEPS), 3: ["Propose"]})
        rep = tracing.span_report(evs)
        assert rep["complete"] == [2] and rep["interior"] == 1


class TestSpanChains:
    def _chain_events(self, heights, skip=()):
        r = FlightRecorder(size=1024)
        for h in heights:
            for step in ("NewHeight", "NewRound", *tracing.REQUIRED_STEPS):
                if (h, step) not in skip:
                    r.record("step", height=h, round=0, step=step)
        return r.events()

    def test_step_chains_and_complete_heights(self):
        evs = self._chain_events([5, 6, 7], skip={(6, "Precommit")})
        chains = tracing.step_chains(evs)
        assert set(chains) == {5, 6, 7}
        assert tracing.complete_heights(chains) == [5, 7]

    def test_block_breakdown_medians(self):
        evs = self._chain_events([1, 2, 3, 4])
        bd = tracing.block_breakdown(evs)
        assert bd is not None
        assert bd["source"] == "flight_recorder"
        assert bd["blocks"] == 3  # heights 1-3 have a next-height Propose
        for k in ("propose_ms", "prevote_ms", "precommit_ms", "commit_ms", "block_ms"):
            assert bd[k] >= 0

    def test_block_breakdown_needs_consecutive_chains(self):
        assert tracing.block_breakdown(self._chain_events([3])) is None
        assert tracing.block_breakdown([]) is None


class TestRPCRoute:
    async def test_dump_flight_recorder_route(self):
        from tendermint_tpu.rpc.core import RPCCore

        class _StubNode:
            flight_recorder = FlightRecorder(size=32)

        node = _StubNode()
        node.flight_recorder.record("step", height=1, round=0, step="Propose")
        core = RPCCore(node)
        snap = await core.call("dump_flight_recorder")
        assert snap["enabled"] is True
        assert snap["events"][0]["kind"] == "step"
        assert snap["events"][0]["height"] == 1
        # seq watermark polling: nothing new -> empty
        again = await core.call("dump_flight_recorder", {"since": snap["next_seq"]})
        assert again["events"] == []

    async def test_route_kinds_filter_anchor_and_moniker(self):
        from tendermint_tpu.rpc.core import RPCCore

        class _Base:
            moniker = "trace-node"

        class _Cfg:
            base = _Base()

        class _StubNode:
            flight_recorder = FlightRecorder(size=32)
            config = _Cfg()

        node = _StubNode()
        node.flight_recorder.record("step", height=1, round=0, step="Propose")
        node.flight_recorder.record("gossip.wakeup", peer="ab")
        node.flight_recorder.record("commit", height=1, txs=0, block="aa")
        core = RPCCore(node)
        # comma-separated string form (what a URL query carries)
        snap = await core.call("dump_flight_recorder", {"kinds": "step,commit"})
        assert [e["kind"] for e in snap["events"]] == ["step", "commit"]
        # list form (programmatic callers)
        snap = await core.call("dump_flight_recorder", {"kinds": ["gossip."]})
        assert [e["kind"] for e in snap["events"]] == ["gossip.wakeup"]
        # the cross-node alignment surface: anchor + node label
        assert set(snap["anchor"]) == {"mono_ns", "wall_ns"}
        assert snap["node"] == "trace-node"

    async def test_route_survives_node_without_recorder(self):
        from tendermint_tpu.rpc.core import RPCCore

        snap = await RPCCore(object()).call("dump_flight_recorder")
        assert snap == {
            "enabled": False, "size": 0, "next_seq": 0, "dropped": 0, "events": [],
        }


class TestVerifyEngineEvents:
    async def test_async_batcher_emits_enqueue_and_flush_spans(self):
        from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        rec = FlightRecorder(size=256)
        # min_device_batch above any test batch: the host path serves, no
        # device compile — this test is about the event stream, not JAX
        bv = BatchVerifier(min_device_batch=1 << 30, recorder=rec)
        svc = AsyncBatchVerifier(bv)
        await svc.start()
        try:
            k = Ed25519PrivKey.from_secret(b"trace")
            msg = b"\x08\x02\x11" + bytes(40)
            assert await svc.verify_one(k.pub_key().bytes(), msg, k.sign(msg))
        finally:
            await svc.stop()
        kinds = [e["kind"] for e in rec.events()]
        assert "verify.enqueue" in kinds
        assert "verify.flush" in kinds
        assert "verify.dispatch" in kinds
        flush = next(e for e in rec.events() if e["kind"] == "verify.flush")
        assert flush["batch"] >= 1 and flush["wait_ms"] >= 0
        dispatch = next(e for e in rec.events() if e["kind"] == "verify.dispatch")
        assert dispatch["path"] == "host" and dispatch["n"] >= 1


class TestFlightSpool:
    """Crash-persistent spool ([instrumentation] flight_spool): rotation
    under the size cap, torn-tail-tolerant replay, wrap accounting, and
    the hot-path contract (the recorder never pays for the spool)."""

    def _steps(self, rec, heights, round_=0):
        for h in heights:
            for s in ("Propose", "Prevote", "Precommit", "Commit"):
                rec.record("step", height=h, step=s, round=round_)
            rec.record("commit", height=h, txs=0, block="ab")

    def test_roundtrip_replay_matches_ring(self, tmp_path):
        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        rec = FlightRecorder(size=4096)
        sp = FlightSpool(str(tmp_path / "flight.spool"), rec, node="n7")
        self._steps(rec, range(1, 8))
        sp.flush()
        sp.close()
        dump = read_spool(str(tmp_path / "flight.spool"))
        assert dump["node"] == "n7" and dump["source"] == "spool"
        assert dump["dropped"] == 0 and dump["torn"] == 0
        assert [e["seq"] for e in dump["events"]] == [
            e["seq"] for e in rec.events()
        ]
        assert dump["anchor"] is not None and dump["anchor"]["wall_ns"] > 0
        rep = tracing.span_report(dump["events"], dropped=dump["dropped"])
        assert rep["bad"] == {} and len(rep["complete"]) == rep["interior"] == 5

    def test_torn_tail_kill_mid_append_keeps_retained_suffix(self, tmp_path):
        """Simulate a SIGKILL landing mid-write: the final record is cut
        at an arbitrary byte.  Replay must keep every complete record,
        count the torn line, and span_report must stay clean."""
        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        path = str(tmp_path / "flight.spool")
        rec = FlightRecorder(size=4096)
        sp = FlightSpool(path, rec, node="torn")
        self._steps(rec, range(1, 6))
        sp.flush()
        # the spool is abandoned un-closed (the kill); chop the file tail
        # mid-record instead of at a line boundary
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)
        dump = read_spool(path)
        assert dump["torn"] == 1
        # every complete line survived: only the final record was cut
        assert len(dump["events"]) >= 5 * 5 - 1
        rep = tracing.span_report(dump["events"], dropped=dump["dropped"])
        assert rep["bad"] == {}
        # garbage bytes appended by a dying disk are skipped the same way
        with open(path, "ab") as f:
            # leading newline: the truncated line above has no terminator,
            # so raw bytes would otherwise merge into the same torn line
            f.write(b"\n\xff\xfe{{{ not json\n")
        dump2 = read_spool(path)
        assert dump2["torn"] == 2
        assert len(dump2["events"]) == len(dump["events"])

    def test_rotation_bounds_disk_and_reports_dropped_prefix(self, tmp_path):
        from tendermint_tpu.libs.tracing import FlightSpool, read_spool, spool_paths

        path = str(tmp_path / "flight.spool")
        rec = FlightRecorder(size=1 << 16)
        cap = 16 * 1024
        sp = FlightSpool(path, rec, size_limit=cap, node="rot")
        for h in range(1, 200):
            self._steps(rec, [h])
            sp.flush()
        sp.close()
        total = sum(os.path.getsize(p) for p in spool_paths(path))
        assert total <= cap, f"spool grew past its cap: {total} > {cap}"
        dump = read_spool(path)
        assert dump["dropped"] > 0  # rotated-away prefix is reported
        assert dump["events"], "the retained suffix must replay"
        # the newest heights survived (oldest-first eviction)
        rep = tracing.span_report(dump["events"], dropped=dump["dropped"])
        assert rep["bad"] == {}, "rotation must only ever truncate a PREFIX"
        assert 198 in tracing.step_chains(dump["events"])

    def test_ring_wrap_between_flushes_is_accounted(self, tmp_path):
        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        rec = FlightRecorder(size=8)
        sp = FlightSpool(str(tmp_path / "w.spool"), rec, node="w")
        for i in range(30):
            rec.record("x", i=i)
        sp.flush()
        for i in range(30):
            rec.record("y", i=i)
        sp.flush()
        sp.close()
        dump = read_spool(str(tmp_path / "w.spool"))
        assert len(dump["events"]) == 16  # two ring-fulls
        assert dump["writer_lost"] == 22  # wrap losses the writer observed
        assert dump["dropped"] == 60 - 16  # replay holes cover all classes

    def test_record_hot_path_unchanged_with_spool_attached(self, tmp_path):
        """The acceptance tripwire: spool writes happen OFF the recording
        path — record() with a spool attached stays under the same 5 µs
        budget the bare recorder is held to."""
        from tendermint_tpu.libs.tracing import FlightSpool

        rec = FlightRecorder(size=8192)
        sp = FlightSpool(str(tmp_path / "hot.spool"), rec, node="hot")
        n = 20_000
        t0 = time.perf_counter()
        for i in range(n):
            rec.record("step", height=i, step="Propose", round=0)
        per_event = (time.perf_counter() - t0) / n
        sp.flush()
        sp.close()
        assert per_event < 5e-6, (
            f"record() with spool enabled took {per_event * 1e6:.2f} us/event"
        )

    def test_flush_idempotent_and_empty_flush_writes_nothing(self, tmp_path):
        from tendermint_tpu.libs.tracing import FlightSpool

        path = str(tmp_path / "idle.spool")
        rec = FlightRecorder(size=64)
        sp = FlightSpool(path, rec, node="idle")
        rec.record("step", height=1, step="Propose")
        assert sp.flush() == 1
        size_after = os.path.getsize(path)
        # nothing new: no bytes written (an idle node must not grow its
        # spool with anchor-only batches every flush interval)
        assert sp.flush() == 0
        sp._group.flush()
        assert os.path.getsize(path) == size_after
        sp.close()

    def test_crash_hooks_flush_on_excepthook(self, tmp_path):
        import sys

        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        path = str(tmp_path / "hook.spool")
        rec = FlightRecorder(size=64)
        sp = FlightSpool(path, rec, node="hook")
        sp.install_crash_hooks()
        try:
            rec.record("step", height=1, step="Propose")
            # simulate the interpreter's unhandled-exception path
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
            dump = read_spool(path)
            assert len(dump["events"]) == 1, "excepthook must flush the spool"
        finally:
            sp.close()
        assert sys.excepthook is sys.__excepthook__ or not hasattr(
            sys.excepthook, "__self__"
        )

    def test_recorder_dropped_property(self):
        rec = FlightRecorder(size=4)
        assert rec.dropped == 0
        for i in range(10):
            rec.record("x", i=i)
        assert rec.dropped == 6

    def test_two_spools_crash_hooks_are_independent(self, tmp_path):
        """In-proc multi-node: removing spool A's crash hook must not
        uninstall spool B's (the excepthook chain is per-object, and only
        the OWNING hook may be restored away)."""
        import sys

        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        rec_a, rec_b = FlightRecorder(size=64), FlightRecorder(size=64)
        sp_a = FlightSpool(str(tmp_path / "a.spool"), rec_a, node="a")
        sp_b = FlightSpool(str(tmp_path / "b.spool"), rec_b, node="b")
        sp_a.install_crash_hooks()
        sp_b.install_crash_hooks()
        try:
            sp_a.close()  # removes A's hooks; B's chain must survive
            assert sys.excepthook is sp_b._hook_fn, (
                "closing spool A must not uninstall spool B's crash hook"
            )
            rec_b.record("step", height=1, step="Propose")
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
            assert len(read_spool(str(tmp_path / "b.spool"))["events"]) == 1
        finally:
            sp_b.close()

    def test_restart_reuses_spool_but_replay_returns_newest_run(self, tmp_path):
        """The spool file survives restarts (append-mode head) while
        recorder seqs restart at 0 per process — the replay must return
        the NEWEST run's events, not let the old run's colliding seqs
        replace the crash evidence with stale data."""
        from tendermint_tpu.libs.tracing import FlightSpool, read_spool

        path = str(tmp_path / "flight.spool")
        # run 1: heights 1-5, clean stop
        rec1 = FlightRecorder(size=4096)
        sp1 = FlightSpool(path, rec1, node="boot1")
        self._steps(rec1, range(1, 6))
        sp1.flush()
        sp1.close()
        # run 2 (restart, same home): heights 100-102, SIGKILLed
        rec2 = FlightRecorder(size=4096)
        sp2 = FlightSpool(path, rec2, node="boot2")
        self._steps(rec2, range(100, 103))
        sp2.flush()  # no close: the crash
        dump = read_spool(path)
        assert dump["runs"] == 2
        assert dump["node"] == "boot2"
        heights = {e.get("height") for e in dump["events"] if e["kind"] == "step"}
        assert heights == {100, 101, 102}, (
            f"replay must carry the crashing run's heights, got {heights}"
        )
        assert len(dump["events"]) == len(rec2.events())
        # legacy single-run spools (and every earlier test) keep working:
        # a one-run file reports runs == 1 with identical semantics
        solo = read_spool(str(tmp_path / "flight.spool") + ".none")
        assert solo["events"] == [] and solo["runs"] == 0
