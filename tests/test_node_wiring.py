"""Node ↔ TPU-engine wiring tests (the BASELINE north-star integration).

Verifies the two loudest round-3 verdict items: (1) a default-config node
boots (rpc import is real, default laddr serves), and (2) a running node
actually exercises its own batch-verify engine — the installed hook, not
the serial host fallback — on the commit-verification and vote-ingress
paths.
"""

import asyncio

from tendermint_tpu.config import Config, test_config as make_test_cfg
from tendermint_tpu.crypto import batch as batch_hook
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "wiring-chain"


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


class TestDefaultConfigBoots:
    async def test_node_starts_with_unmodified_config(self, tmp_path):
        """Node(Config(), gen).start() must not raise — round-3 verdict: the
        dead rpc import made every default-config node crash on start."""
        pv = MockPV()
        cfg = Config(home=str(tmp_path / "default-home"))
        node = Node(cfg, _gen([pv]), priv_validator=pv)
        try:
            await node.start()
            # default config serves RPC on 26657 and installs the engine
            assert node.rpc_server is not None
            assert node.batch_verifier is not None
            assert batch_hook.get_verifier() == node.batch_verifier.verify

            async def first_block():
                while node.block_store.height() < 1:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(first_block(), 30.0)
        finally:
            await node.stop()
        # engine uninstalled on stop
        assert batch_hook.get_verifier() == batch_hook.host_batch_verify


class TestEngineWiring:
    async def test_net_runs_on_installed_engine(self, tmp_path):
        """4-validator net with cfg.tpu.enabled: every node's consensus
        reactor carries the AsyncBatchVerifier, the process-wide hook is a
        BatchVerifier.verify (device path), and it is actually called on
        the live vote/commit paths."""
        pvs = sorted([MockPV() for _ in range(4)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = []
        calls = {"n": 0}

        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"eng{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            cfg.tpu.enabled = True
            cfg.tpu.flush_interval = 0.002
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for node in nodes:
                await node.start()
                # wrap the installed engine to count real invocations
                bv = node.batch_verifier
                assert bv is not None
                orig = bv.verify

                def counting(pubkeys, msgs, sigs, _orig=orig):
                    calls["n"] += 1
                    return _orig(pubkeys, msgs, sigs)

                bv.verify = counting
                batch_hook.set_verifier(counting)
                node.async_verifier.verifier = bv
                assert node.consensus_reactor.async_verifier is node.async_verifier
            for i in range(4):
                for j in range(i + 1, 4):
                    addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
                    await nodes[i].switch.dial_peer(addr)

            async def all_at(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(all_at(3), 90.0)
            # the engine did the verifying: gossiped votes and commit
            # verification flow through the installed hook
            assert calls["n"] > 0, "installed BatchVerifier was never called"
            for h in range(1, 4):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1
        finally:
            batch_hook.set_verifier(None)
            for node in nodes:
                if node.is_running:
                    await node.stop()
