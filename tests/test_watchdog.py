"""Health watchdog (libs/watchdog.py): detector semantics, the monotonic
vs wall clock discipline under a chaos SkewedClock, alarm transitions and
the served /health + /status surface.

The clock tests are the load-bearing ones: a constant injected wall skew
models a node whose clock has ALWAYS been wrong (NTP late since boot) —
that node is healthy and must not alarm; a runtime skew step models the
clock moving under a running node — that IS drift.  And because stall
intervals are measured on the monotonic clock, no wall skew of any size
may fake or mask a consensus stall.
"""

import asyncio
import os
import tarfile

from tendermint_tpu.chaos.clock import Clock, SkewedClock
from tendermint_tpu.libs.tracing import FlightRecorder
from tendermint_tpu.libs.watchdog import ALARM_SEVERITY, Watchdog


class _BlockStore:
    def __init__(self, h=1):
        self.h = h

    def height(self):
        return self.h


class _RS:
    def __init__(self):
        self.height = 1
        self.round = 0


class _CS:
    def __init__(self):
        self.is_running = True
        self.rs = _RS()
        self.clock = Clock()


class _Switch:
    def __init__(self, n=0):
        self.n = n

    def num_peers(self):
        return self.n


class _Mempool:
    def __init__(self, size=0, cap=100):
        self._size = size
        self.size_limit = cap

    def size(self):
        return self._size


class _Prof:
    lag_samples = 1
    last_lag_ms = 0.0


class _StubNode:
    """The exact surface the watchdog probes, nothing else."""

    def __init__(self):
        self.consensus = _CS()
        self.block_store = _BlockStore()
        self.switch = None
        self.async_verifier = None
        self.loop_profiler = None
        self.mempool = None
        self.statesync_reactor = None
        self.blockchain_reactor = None


class TestDetectors:
    def test_stall_fires_after_threshold_and_clears_on_commit(self):
        node = _StubNode()
        rec = FlightRecorder(size=128)
        wd = Watchdog(node, stall_seconds=5.0, recorder=rec)
        t = 1000.0
        assert wd.check(now=t)["verdict"] == "ok"
        assert wd.check(now=t + 4.9)["verdict"] == "ok"  # under the bound
        h = wd.check(now=t + 5.1)
        assert h["verdict"] == "critical"  # stall is a critical alarm
        assert "consensus_stall" in h["alarms"]
        assert h["alarms"]["consensus_stall"]["severity"] == "critical"
        # tip advances -> alarm clears, verdict recovers
        node.block_store.h = 2
        h = wd.check(now=t + 6.0)
        assert h["verdict"] == "ok" and h["alarms"] == {}
        kinds = [ev["kind"] for ev in rec.events()]
        assert "health.alarm" in kinds and "health.clear" in kinds
        alarm_ev = next(ev for ev in rec.events() if ev["kind"] == "health.alarm")
        assert alarm_ev["alarm"] == "consensus_stall"

    def test_stall_suppressed_on_idle_wait_for_txs_node(self):
        """A [consensus] create_empty_blocks=false node with an empty
        mempool legitimately parks between heights: no CRITICAL alarm for
        a healthy idle node (a load balancer acting on it would guarantee
        it stays idle forever).  And when a tx finally arrives, the stall
        clock starts THEN — not 10 idle minutes ago."""
        node = _StubNode()
        node.consensus.config = type(
            "C", (), {"wait_for_txs": staticmethod(lambda: True)}
        )()
        node.mempool = _Mempool(size=0, cap=100)
        wd = Watchdog(node, stall_seconds=5.0)
        t = 0.0
        wd.check(now=t)
        assert wd.check(now=t + 600.0)["alarms"] == {}, "idle is healthy"
        # a tx lands: detector re-arms with a FRESH baseline
        node.mempool._size = 1
        assert wd.check(now=t + 601.0)["alarms"] == {}
        assert wd.check(now=t + 605.0)["alarms"] == {}  # 4s < bound
        h = wd.check(now=t + 607.0)  # 6s of pending tx, no commit: stall
        assert "consensus_stall" in h["alarms"]

    def test_stall_suppressed_while_syncing(self):
        node = _StubNode()

        class _BR:
            fast_sync = True
            wait_statesync = False

        node.blockchain_reactor = _BR()
        wd = Watchdog(node, stall_seconds=1.0)
        t = 0.0
        wd.check(now=t)
        # a fastsyncing node's tip "stalls" by design: no alarm
        assert wd.check(now=t + 100.0)["verdict"] == "ok"

    def test_round_churn_is_degraded_not_critical(self):
        node = _StubNode()
        wd = Watchdog(node, stall_seconds=1e9, round_churn=4)
        node.consensus.rs.round = 3
        assert wd.check(now=1.0)["verdict"] == "ok"
        node.consensus.rs.round = 4
        h = wd.check(now=2.0)
        assert h["verdict"] == "degraded"
        assert "round_churn" in h["alarms"]

    def test_peer_collapse_relative_to_peak(self):
        node = _StubNode()
        node.switch = _Switch(0)
        wd = Watchdog(node, stall_seconds=1e9, min_peers=2)
        assert wd.check(now=1.0)["verdict"] == "ok"  # never had peers
        node.switch.n = 6
        assert wd.check(now=2.0)["verdict"] == "ok"
        node.switch.n = 3  # exactly half: not collapse
        assert wd.check(now=3.0)["verdict"] == "ok"
        node.switch.n = 2  # below half the peak
        h = wd.check(now=4.0)
        assert "peer_collapse" in h["alarms"]
        node.switch.n = 5
        assert wd.check(now=5.0)["alarms"] == {}

    def test_mempool_saturation(self):
        node = _StubNode()
        node.mempool = _Mempool(size=89, cap=100)
        wd = Watchdog(node, stall_seconds=1e9, mempool_ratio=0.9)
        assert wd.check(now=1.0)["alarms"] == {}
        node.mempool._size = 90
        assert "mempool_saturation" in wd.check(now=2.0)["alarms"]
        node.mempool._size = 10
        assert wd.check(now=3.0)["alarms"] == {}

    def test_loop_lag_needs_two_consecutive_breaches(self):
        node = _StubNode()
        node.loop_profiler = _Prof()
        wd = Watchdog(node, stall_seconds=1e9, lag_ms=100.0)
        node.loop_profiler.last_lag_ms = 500.0
        assert wd.check(now=1.0)["alarms"] == {}  # one breach = a burst
        node.loop_profiler.last_lag_ms = 40.0
        assert wd.check(now=2.0)["alarms"] == {}  # breach streak reset
        node.loop_profiler.last_lag_ms = 500.0
        wd.check(now=3.0)
        h = wd.check(now=4.0)  # second consecutive breach
        assert "loop_lag" in h["alarms"]

    def test_ingress_shedding_sustained_rate(self):
        node = _StubNode()

        class _Core:
            throttled_total = 0

        class _Server:
            core = _Core()

        node.rpc_server = _Server()
        wd = Watchdog(node, stall_seconds=1e9, shed_rate=5.0)
        wd.check(now=0.0)  # baseline sample
        _Core.throttled_total = 100  # 100 rejections in 1s: breach 1
        assert wd.check(now=1.0)["alarms"] == {}  # one burst: no flap
        _Core.throttled_total = 200  # sustained: breach 2
        h = wd.check(now=2.0)
        assert "ingress_shedding" in h["alarms"]
        assert h["verdict"] == "degraded"
        _Core.throttled_total = 201  # 1/s: under the bound -> clears
        assert wd.check(now=3.0)["alarms"] == {}
        # trickle below the bound never alarms
        for i in range(4, 10):
            _Core.throttled_total += 2
            assert wd.check(now=float(i))["alarms"] == {}

    async def test_verify_stall_from_pending_queue_age(self):
        node = _StubNode()
        loop = asyncio.get_event_loop()

        class _AV:
            _pending = [(b"", b"", b"", None, loop.time() - 10.0)]

        node.async_verifier = _AV()
        wd = Watchdog(node, stall_seconds=1e9, verify_stall_seconds=5.0)
        h = wd.check(now=1.0)
        assert "verify_stall" in h["alarms"]
        assert h["verdict"] == "critical"
        node.async_verifier._pending = []
        assert wd.check(now=2.0)["verdict"] == "ok"


class TestClockDiscipline:
    """The satellite's pinned contract: SkewedClock must not false-trip
    the stall/drift detectors — monotonic vs wall discipline."""

    def test_constant_skew_never_trips_drift(self):
        node = _StubNode()
        node.consensus.clock = SkewedClock(3600.0)  # an hour wrong since boot
        wd = Watchdog(node, stall_seconds=1e9, clock_drift_seconds=2.0)
        for i in range(5):
            assert wd.check(now=float(i))["alarms"] == {}, "constant skew is not drift"

    def test_runtime_skew_step_trips_drift_and_unstep_clears(self):
        node = _StubNode()
        clock = SkewedClock(0.0)
        node.consensus.clock = clock
        wd = Watchdog(node, stall_seconds=1e9, clock_drift_seconds=2.0)
        assert wd.check(now=0.0)["alarms"] == {}
        clock.set_skew(5.0)  # the clock MOVED under a running node
        h = wd.check(now=1.0)
        assert "clock_drift" in h["alarms"]
        assert h["alarms"]["clock_drift"]["severity"] == "degraded"
        clock.set_skew(0.0)
        assert wd.check(now=2.0)["alarms"] == {}

    def test_wall_skew_cannot_fake_or_mask_a_stall(self):
        # stall intervals are monotonic: a huge wall skew with a healthy
        # tip must not alarm, and a real stall must alarm regardless of
        # any skew trying to "roll back" time
        node = _StubNode()
        node.consensus.clock = SkewedClock(-86400.0)
        wd = Watchdog(node, stall_seconds=5.0, clock_drift_seconds=1e18)
        t = 0.0
        wd.check(now=t)
        node.block_store.h += 1
        # advancing: healthy despite a day of wall skew
        assert wd.check(now=t + 4.0)["alarms"] == {}
        # stop advancing; jump the wall clock forward mid-window — the
        # monotonic stall math must neither trip early nor late
        node.consensus.clock.set_skew(86400.0)
        assert "consensus_stall" not in wd.check(now=t + 8.9)["alarms"]  # 4.9s stale
        assert "consensus_stall" in wd.check(now=t + 9.2)["alarms"]  # 5.2s stale


class TestTransitionsAndAutodump:
    def test_severity_table_covers_every_alarm(self):
        assert set(ALARM_SEVERITY) == {
            "consensus_stall", "verify_stall", "round_churn", "peer_collapse",
            "loop_lag", "mempool_saturation", "ingress_shedding", "clock_drift",
            "disk_fault", "disk_pressure",
        }
        assert ALARM_SEVERITY["consensus_stall"] == "critical"
        assert ALARM_SEVERITY["verify_stall"] == "critical"
        assert ALARM_SEVERITY["disk_fault"] == "critical"
        assert ALARM_SEVERITY["disk_pressure"] == "degraded"

    def test_autodump_fires_on_critical_transition_rate_bounded(self):
        node = _StubNode()
        dumps = []
        wd = Watchdog(
            node, stall_seconds=5.0,
            autodump_fn=lambda health: dumps.append(health) or "x",
            autodump_min_interval=60.0,
        )
        t = 0.0
        wd.check(now=t)
        wd.check(now=t + 6.0)  # critical: dump 1
        assert len(dumps) == 1 and dumps[0]["verdict"] == "critical"
        node.block_store.h += 1
        wd.check(now=t + 7.0)  # recovers
        wd.check(now=t + 20.0)  # stalls again -> critical, but rate-bounded
        assert len(dumps) == 1, "flapping critical must not spam bundles"
        node.block_store.h += 1
        wd.check(now=t + 21.0)
        wd.check(now=t + 90.0)  # past the rate bound: allowed again
        assert len(dumps) == 2

    def test_autodump_failure_does_not_kill_the_watchdog(self):
        node = _StubNode()

        def boom(health):
            raise OSError("disk full")

        wd = Watchdog(node, stall_seconds=5.0, autodump_fn=boom)
        wd.check(now=0.0)
        h = wd.check(now=6.0)  # must not raise
        assert h["verdict"] == "critical"

    def test_write_autodump_bundle_contents(self, tmp_path):
        from tendermint_tpu.libs.watchdog import write_autodump_bundle

        node = _StubNode()
        node.flight_recorder = FlightRecorder(size=32)
        node.flight_recorder.record("step", height=1, step="Propose")
        path = write_autodump_bundle(node, {"verdict": "critical"}, str(tmp_path))
        assert os.path.exists(path)
        with tarfile.open(path) as tar:
            names = {os.path.basename(m.name) for m in tar.getmembers()}
        assert {"health.json", "recorder.json", "consensus.json"} <= names


class TestLiveNode:
    async def test_health_route_and_status_block(self, tmp_path):
        """A real single-validator node: /health serves the verdict, and
        /status carries the health summary block readiness gates poll."""
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node
        from tendermint_tpu.rpc import LocalClient
        from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
        from tendermint_tpu.types.params import BlockParams, ConsensusParams

        pv = MockPV()
        gen = GenesisDoc(
            chain_id="wd-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
            consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        )
        cfg = make_test_cfg(str(tmp_path / "wd"))
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.05
        cfg.instrumentation.watchdog_interval = 0.1
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        await node.start()
        try:
            assert node.watchdog is not None and node.watchdog.is_running

            async def committed(h):
                while node.block_store.height() < h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(committed(2), 20.0)
            await asyncio.sleep(0.25)  # a couple of watchdog ticks
            c = LocalClient(node)
            hl = await c.health()
            assert hl["verdict"] == "ok" and hl["ok"] is True
            assert hl["alarms"] == {} and hl["ticks"] >= 1
            st = await c.status()
            assert st["health"] == {"verdict": "ok", "alarms": []}
        finally:
            await node.stop()
