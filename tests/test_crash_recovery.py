"""Crash-recovery rigs (VERDICT #5; reference: consensus/replay_test.go
crashingWAL + test/persist/test_failure_indices.sh + byzantine_test.go:27).

(a) crashing-WAL: kill consensus at every WAL record index, restart on the
    same stores, assert resume past the crash height.
(b) fail-point kills: real subprocess os._exit at each FAIL_TEST_INDEX
    crash site (finalize-*/applyblock-*), restart, assert recovery.
(c) byzantine proposer: conflicting proposals to different peers via the
    overridable decide_proposal; honest majority keeps committing.
"""

import asyncio
import os
import subprocess
import sys
import time

import pytest

import tendermint_tpu.node as node_module
from tendermint_tpu.cli import main as cli_main
from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class WALCrash(Exception):
    pass


class CrashingWAL(WAL):
    """replay_test.go crashingWAL: raise on the Nth write, passthrough
    otherwise.  Class-level countdown so a fresh instance per node start
    still honors the schedule."""

    crash_after = -1  # set by the test; -1 = disabled

    def __init__(self, path):
        super().__init__(path)

    def _tick(self):
        cls = CrashingWAL
        if cls.crash_after < 0:
            return
        if cls.crash_after == 0:
            cls.crash_after = -1
            raise WALCrash("simulated WAL crash")
        cls.crash_after -= 1

    def write(self, payload):
        self._tick()
        super().write(payload)

    def write_sync(self, payload):
        self._tick()
        super().write_sync(payload)


def _solo_cfg(tmp_path, name):
    cfg = make_test_cfg(str(tmp_path / name))
    cfg.base.db_backend = "sqlite"
    cfg.rpc.laddr = ""
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_commit = 0.02
    cfg.ensure_dirs()
    return cfg


def _gen(pvs, chain="crash-chain"):
    return GenesisDoc(
        chain_id=chain,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


class TestCrashingWAL:
    async def test_crash_at_every_wal_record_then_recover(self, tmp_path, monkeypatch):
        """Run a solo validator; for each crash index N, crash the WAL at
        record N mid-flight, restart on the same home, and require progress
        beyond the pre-crash height.  One shared home so each iteration
        also exercises handshake catchup over the previous history."""
        monkeypatch.setattr(node_module, "WAL", CrashingWAL)
        pv = MockPV()
        gen = _gen([pv])
        home_i = 0
        for crash_n in range(1, 14, 2):
            home_i += 1
            cfg = _solo_cfg(tmp_path, f"wal{home_i}")
            CrashingWAL.crash_after = crash_n
            node = Node(cfg, gen, priv_validator=pv)
            await node.start()
            # consensus dies at the Nth WAL record (receive loop exits)
            await asyncio.wait_for(node.consensus.wait_done(), 30.0)
            crashed_height = node.block_store.height()
            await node.stop()

            # restart clean on the same stores: WAL catchup + handshake
            CrashingWAL.crash_after = -1
            node2 = Node(cfg, gen, priv_validator=pv)
            await node2.start()

            async def past(h):
                while node2.block_store.height() < h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(past(crashed_height + 2), 30.0)
            await node2.stop()


@pytest.mark.parametrize("indices", [range(0, 5), range(5, 10)])
class TestFailPointKills:
    def test_kill_and_recover(self, tmp_path, indices):
        """test_failure_indices.sh: run the node subprocess with
        FAIL_TEST_INDEX=i (hard os._exit at crash site i), then restart
        without it and require 2 more committed blocks."""
        home = str(tmp_path / "fp-home")
        assert cli_main(["--home", home, "init", "--chain-id", "fp-chain"]) == 0
        runner = os.path.join(REPO, "tests", "failpoint_node.py")
        base_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        base_env.pop("FAIL_TEST_INDEX", None)

        for i in indices:
            crash = subprocess.run(
                [sys.executable, runner, "--home", home, "--blocks", "3"],
                env={**base_env, "FAIL_TEST_INDEX": str(i)},
                capture_output=True,
                timeout=90,
                text=True,
            )
            assert crash.returncode == 1, (
                f"index {i}: expected fail-point exit, got rc={crash.returncode}\n"
                f"{crash.stdout}\n{crash.stderr}"
            )
            recover = subprocess.run(
                [sys.executable, runner, "--home", home, "--blocks", "2"],
                env=base_env,
                capture_output=True,
                timeout=90,
                text=True,
            )
            assert recover.returncode == 0, (
                f"index {i}: recovery failed rc={recover.returncode}\n"
                f"{recover.stdout}\n{recover.stderr}"
            )


class TestByzantineProposer:
    async def test_conflicting_proposals_do_not_halt_net(self, tmp_path):
        """byzantine_test.go:27 — node0 equivocates: proposal A (+parts) to
        one peer, proposal B to the others.  With 3 of 4 honest the network
        must keep committing and stay consistent."""
        from tests.test_consensus_net import make_net, stop_net, wait_all_height

        nodes, pvs = await make_net(tmp_path, 4, name="byzprop")
        byz = nodes[0]
        cs = byz.consensus
        reactor = byz.consensus_reactor

        from tendermint_tpu.consensus.reactor import DATA_CHANNEL, _enc
        from tendermint_tpu.types import BlockID
        from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES
        from tendermint_tpu.types.proposal import Proposal

        async def byz_decide_proposal(height, round_):
            created = cs._create_proposal_block()
            if created is None:
                return
            block_a, parts_a = created
            # a second, conflicting block with different data
            commit = (
                cs.rs.last_commit.make_commit()
                if height > 1 and cs.rs.last_commit is not None
                else __import__(
                    "tendermint_tpu.types.block", fromlist=["Commit"]
                ).Commit(0, 0, BlockID(), [])
            )
            block_b = cs.sm_state.make_block(
                height, [b"byz-conflicting-tx"], commit, [], pvs[0].address()
            )
            parts_b = block_b.make_part_set(BLOCK_PART_SIZE_BYTES)

            peers = list(byz.switch.peers.values())
            half = max(1, len(peers) // 2)
            for grp, (blk, parts) in (
                (peers[:half], (block_a, parts_a)),
                (peers[half:], (block_b, parts_b)),
            ):
                prop = Proposal(
                    height=height,
                    round=round_,
                    pol_round=cs.rs.valid_round,
                    block_id=BlockID(blk.hash(), parts.header()),
                    timestamp_ns=time.time_ns(),
                )
                pvs[0].sign_proposal(cs.sm_state.chain_id, prop)
                for peer in grp:
                    await peer.send(DATA_CHANNEL, _enc("proposal", {"proposal": prop.to_dict()}))
                    for i in range(parts.total):
                        await peer.send(
                            DATA_CHANNEL,
                            _enc("block_part", {
                                "height": height, "round": round_,
                                "part": parts.get_part(i).to_dict(),
                            }),
                        )

        cs.decide_proposal = byz_decide_proposal
        try:
            start = max(n.block_store.height() for n in nodes)
            # honest nodes (1-3) must keep committing identical blocks
            await wait_all_height(nodes[1:], start + 4, timeout=60.0)
            for h in range(1, start + 4):
                hashes = {
                    n.block_store.load_block(h).hash()
                    for n in nodes[1:]
                    if n.block_store.load_block(h) is not None
                }
                assert len(hashes) <= 1, f"honest nodes diverged at {h}"
        finally:
            await stop_net(nodes)


class TestRestartOverWALBitRot:
    async def test_node_restarts_and_commits_over_mid_wal_corruption(self, tmp_path):
        """CrashingWAL-rig extension for the hostile-disk contract: a solo
        validator stops cleanly, ONE byte inside an early WAL record rots
        on disk, and the restart must come up and keep committing — the
        tolerant replay resyncs past the damaged region (and counts it)
        instead of refusing to boot or replaying garbage."""
        from tendermint_tpu.libs.autofile import walk_frames

        pv = MockPV()
        gen = _gen([pv], chain="walrot-chain")
        cfg = _solo_cfg(tmp_path, "walrot")
        node = Node(cfg, gen, priv_validator=pv)
        await node.start()

        async def past(n, h):
            while n.block_store.height() < h:
                await asyncio.sleep(0.02)

        await asyncio.wait_for(past(node, 3), 30.0)
        stopped_height = node.block_store.height()
        await node.stop()

        wal_path = cfg.wal_file()
        raw = bytearray(open(wal_path, "rb").read())
        offsets = [pos for k, pos, _ in walk_frames(bytes(raw)) if k == "record"]
        assert len(offsets) > 4
        raw[offsets[1] + 12] ^= 0xFF  # rot an EARLY record, mid-file
        open(wal_path, "wb").write(bytes(raw))

        node2 = Node(cfg, gen, priv_validator=pv)
        await node2.start()
        try:
            await asyncio.wait_for(past(node2, stopped_height + 2), 30.0)
            assert node2.consensus.wal.corrupt_regions_skipped >= 1
        finally:
            await node2.stop()


class TestWALFuzz:
    """consensus/wal_fuzz.go flavor: corrupted/torn WALs must either
    recover cleanly (torn tail = crash mid-write) or fail LOUDLY
    (mid-file corruption) — never silently misreplay."""

    def _wal(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        wal = WAL(str(tmp_path / "cs.wal" / "wal"))
        for h in (1, 2):
            wal.write_sync({"type": "msg", "height": h, "data": b"x" * 100})
            wal.write_end_height(h)
        wal.write_sync({"type": "msg", "height": 3, "data": b"y" * 100})
        wal.close()
        return str(tmp_path / "cs.wal" / "wal")

    def test_torn_tail_recovers(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        path = self._wal(tmp_path)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[:-37])  # tear the last record mid-payload
        wal = WAL(path)
        records, found = wal.search_for_end_height(2)
        assert found
        assert records == []  # the torn height-3 msg is gone, cleanly
        # the WAL is appendable again after the torn read
        wal.write_sync({"type": "msg", "height": 3, "data": b"z"})
        assert wal.all_records()[-1]["height"] == 3
        wal.close()

    def test_mid_file_corruption_is_loud(self, tmp_path):
        import pytest as _pytest

        from tendermint_tpu.consensus.wal import WAL, WALCorruptionError

        path = self._wal(tmp_path)
        raw = bytearray(open(path, "rb").read())
        raw[40] ^= 0xFF  # flip a byte inside the first record's payload
        open(path, "wb").write(bytes(raw))
        wal = WAL(path)
        with _pytest.raises(WALCorruptionError):
            wal.all_records()
        wal.close()

    def test_random_garbage_never_misreplays(self, tmp_path):
        """Random mutations: every outcome is either a clean parse of a
        PREFIX of the original records or a WALCorruptionError — fuzzing
        the decoder invariant."""
        import random

        from tendermint_tpu.consensus.wal import WAL, WALCorruptionError

        path = self._wal(tmp_path)
        original = open(path, "rb").read()
        from tendermint_tpu.consensus.wal import decode_records

        full = list(decode_records(original))
        rng = random.Random(5)
        for _ in range(60):
            raw = bytearray(original)
            op = rng.randrange(3)
            if op == 0:  # truncate
                del raw[rng.randrange(1, len(raw)) :]
            elif op == 1:  # flip a byte
                raw[rng.randrange(len(raw))] ^= rng.randrange(1, 256)
            else:  # insert garbage
                pos = rng.randrange(len(raw))
                raw[pos:pos] = bytes(rng.randrange(256) for _ in range(8))
            try:
                got = list(decode_records(bytes(raw)))
            except WALCorruptionError:
                continue  # loud failure: acceptable
            except Exception:
                continue  # decoder surfaced garbage as an error: acceptable
            # silent success must be a strict prefix of the original
            assert got == full[: len(got)], "misreplayed/mutated records"
