"""Cross-node trace merging (libs/tracemerge.py): dump loading, clock
alignment with deliberately skewed anchors (chaos SkewedClock), out-of-
order/overlapping dumps, per-height attribution plumbing, the trace-net
check gate, and a deterministic 4-node in-proc net whose merged timeline
must produce a complete per-height chain."""

import asyncio
import json
import random
import time

import pytest

from tendermint_tpu.chaos.clock import SkewedClock
from tendermint_tpu.libs import tracemerge
from tendermint_tpu.libs.tracing import FlightRecorder


def _synthetic_dump(node, heights, anchor_wall_ns=10_000_000_000,
                    commit_ns=1_000_000_000, shuffle=None):
    """A dump whose commits land at t_ns = h*commit_ns on a mono scale
    anchored at mono_ns=0 → wall = anchor_wall_ns + h*commit_ns."""
    events = []
    seq = 0
    for h in heights:
        for step in ("Propose", "Prevote", "Precommit", "Commit"):
            events.append({"seq": seq, "t_ns": h * commit_ns - 1000 + seq,
                           "kind": "step", "height": h, "round": 0, "step": step})
            seq += 1
        events.append({"seq": seq, "t_ns": h * commit_ns, "kind": "commit",
                       "height": h, "txs": 0, "block": f"hash{h}"})
        seq += 1
        events.append({"seq": seq, "t_ns": h * commit_ns + 500, "kind": "proposal",
                       "height": h + 1, "round": 0,
                       "src": "self" if h % 2 else "ab12cd34"})
        seq += 1
    if shuffle is not None:
        random.Random(shuffle).shuffle(events)
    return {
        "enabled": True, "size": 8192, "next_seq": seq, "dropped": 0,
        "anchor": {"mono_ns": 0, "wall_ns": anchor_wall_ns},
        "events": events, "node": node,
    }


class TestLoadDump:
    def test_raw_and_rpc_wrapped_and_naming(self, tmp_path):
        raw = _synthetic_dump("", [1, 2])
        del raw["node"]
        p1 = tmp_path / "n0.json"
        p1.write_text(json.dumps(raw))
        d = tracemerge.load_dump(str(p1))
        assert d["node"] == "n0"  # file stem when the dump carries no name
        assert [e["seq"] for e in d["events"]] == sorted(
            e["seq"] for e in d["events"]
        )
        # JSON-RPC response wrapping (curl output saved verbatim)
        p2 = tmp_path / "wrapped.json"
        p2.write_text(json.dumps({"jsonrpc": "2.0", "id": 1,
                                  "result": _synthetic_dump("rpc-node", [1])}))
        d = tracemerge.load_dump(str(p2))
        assert d["node"] == "rpc-node"
        d = tracemerge.load_dump(str(p2), name="override")
        assert d["node"] == "override"

    def test_rejects_non_dump(self, tmp_path):
        p = tmp_path / "junk.json"
        p.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="not a flight-recorder dump"):
            tracemerge.load_dump(str(p))


class TestClockAlignment:
    def test_estimate_offsets_recovers_anchor_skew(self):
        # three nodes committing simultaneously; node2's anchor is 5 s
        # ahead (a wrong wall clock at dump time)
        dumps = [
            _synthetic_dump("n0", range(1, 8)),
            _synthetic_dump("n1", range(1, 8)),
            _synthetic_dump("n2", range(1, 8),
                            anchor_wall_ns=15_000_000_000),
        ]
        offsets = tracemerge.estimate_offsets(dumps)
        # median reference = the honest pair, so their offsets are ~0 and
        # the skewed node's is ~+5 s
        assert abs(offsets[0]) < 1_000_000
        assert abs(offsets[1]) < 1_000_000
        assert abs(offsets[2] - 5_000_000_000) < 1_000_000

    def test_merge_corrects_skew_and_reports_it(self):
        dumps = [
            _synthetic_dump("n0", range(1, 8)),
            _synthetic_dump("n1", range(1, 8)),
            _synthetic_dump("n2", range(1, 8), anchor_wall_ns=15_000_000_000),
        ]
        merged = tracemerge.merge(dumps)
        # the skew is VISIBLE in the per-node offsets...
        assert merged["offsets_ms"][2] == pytest.approx(5000.0, abs=1.0)
        # ...and corrected out of the timeline: commits were simultaneous
        assert merged["commit_skew_ms_p90"] == pytest.approx(0.0, abs=1.0)
        # without causal alignment the raw anchors put n2 5 s late
        raw = tracemerge.merge(
            [_synthetic_dump("n0", range(1, 8)),
             _synthetic_dump("n1", range(1, 8)),
             _synthetic_dump("n2", range(1, 8), anchor_wall_ns=15_000_000_000)],
            causal=False,
        )
        assert raw["commit_skew_ms_p90"] == pytest.approx(5000.0, abs=1.0)

    def test_skewed_clock_anchor_end_to_end(self):
        # REAL recorders, one dumping through a chaos SkewedClock — the
        # rig-level fault tracemerge's causal pass must detect+correct
        skew_s = 2.0
        recs = [
            FlightRecorder(size=256),
            FlightRecorder(size=256),
            FlightRecorder(size=256, wall_ns_fn=SkewedClock(skew_s).time_ns),
        ]
        for h in range(1, 7):
            for r in recs:  # near-simultaneous commit landmarks
                r.record("commit", height=h, txs=0, block=f"h{h}")
            time.sleep(0.002)
        dumps = []
        for i, r in enumerate(recs):
            snap = r.snapshot()
            snap["node"] = f"n{i}"
            dumps.append(snap)
        offsets = tracemerge.estimate_offsets(dumps)
        assert offsets[2] / 1e9 == pytest.approx(skew_s, abs=0.1)
        merged = tracemerge.merge(dumps)
        # corrected: commits recorded back-to-back must align to ~0 skew,
        # far below the injected 2000 ms
        assert merged["commit_skew_ms_p90"] < 100.0
        assert merged["offsets_ms"][2] == pytest.approx(skew_s * 1000, abs=100)

    def test_anchorless_dumps_do_not_crash(self):
        d0 = _synthetic_dump("old0", [1, 2, 3])
        d1 = _synthetic_dump("old1", [1, 2, 3])
        del d0["anchor"], d1["anchor"]
        merged = tracemerge.merge([d0, d1])
        assert merged["offsets_ms"] == [0.0, 0.0]
        assert merged["commit_skew_ms_p90"] is None


class TestOutOfOrderAndOverlap:
    def test_shuffled_events_and_different_height_windows(self):
        # n0 covers 1..6, n1 covers 3..9 with a 5 s anchor error; both
        # dumps' event lists arrive SHUFFLED
        d0 = _synthetic_dump("n0", range(1, 7), shuffle=13)
        d1 = _synthetic_dump("n1", range(3, 10), shuffle=37,
                             anchor_wall_ns=15_000_000_000)
        merged = tracemerge.merge([d0, d1])
        assert sorted(merged["heights"]) == list(range(1, 10))
        # overlap window drives the offsets: the two nodes split the 5 s
        # anchor disagreement symmetrically (median of a pair = midpoint)
        assert merged["offsets_ms"][1] - merged["offsets_ms"][0] == pytest.approx(
            5000.0, abs=1.0
        )
        for h in range(3, 7):  # shared heights align to ~zero skew
            assert merged["heights"][h]["commit_skew_ms"] == pytest.approx(
                0.0, abs=1.0
            )
        # heights outside the overlap still carry their single commit
        assert "commit_ms" in merged["heights"][1]["nodes"]["n0"]
        assert "commit_ms" in merged["heights"][9]["nodes"]["n1"]

    def test_hash_mismatch_detected(self):
        d0 = _synthetic_dump("n0", [1, 2, 3])
        d1 = _synthetic_dump("n1", [1, 2, 3])
        for ev in d1["events"]:
            if ev["kind"] == "commit" and ev["height"] == 2:
                ev["block"] = "DIFFERENT"
        merged = tracemerge.merge([d0, d1])
        assert merged["hash_mismatch_heights"] == [2]
        assert merged["heights"][2]["hash_mismatch"] == ["DIFFERENT", "hash2"]
        failures = tracemerge.check([d0, d1], merged, require_attribution=False)
        assert any("hash mismatch" in f for f in failures)


class TestAttributionPlumbing:
    def _dump_with_profiler(self):
        d = _synthetic_dump("n0", [1, 2, 3, 4])
        # one loop.busy + one loop.lag inside every commit interval
        extra = []
        for h in (1, 2, 3):
            mid = h * 1_000_000_000 + 500_000_000
            extra.append({"seq": 900 + h * 2, "t_ns": mid, "kind": "loop.busy",
                          "interval_ms": 250.0, "consensus_ms": 400.0,
                          "gossip_ms": 100.0})
            extra.append({"seq": 901 + h * 2, "t_ns": mid + 1000,
                          "kind": "loop.lag", "lag_ms": 50.0})
        d["events"].extend(extra)
        return d

    def test_attribution_by_height_and_median(self):
        by_h = tracemerge.attribution_by_height(self._dump_with_profiler())
        assert sorted(by_h) == [2, 3, 4]  # keyed by interval-ENDING height
        for att in by_h.values():
            assert att["wall_ms"] == pytest.approx(1000.0)
            assert att["consensus_pct"] == pytest.approx(40.0)
            assert att["gossip_pct"] == pytest.approx(10.0)
            total = sum(v for k, v in att.items() if k.endswith("_pct"))
            assert total == pytest.approx(100.0, abs=0.5)
        med = tracemerge.median_attribution(by_h)
        assert med["consensus_pct"] == pytest.approx(40.0)
        assert tracemerge.median_attribution({}) is None

    def test_non_consecutive_heights_skipped(self):
        d = _synthetic_dump("n0", [1, 2, 5, 6])
        assert sorted(tracemerge.attribution_by_height(d)) == []  # no loop evs
        d = self._dump_with_profiler()
        d["events"] = [e for e in d["events"]
                       if not (e["kind"] == "commit" and e["height"] == 3)]
        assert 3 not in tracemerge.attribution_by_height(d)

    def test_check_requires_attribution_on_some_node(self):
        plain = _synthetic_dump("n0", [1, 2, 3, 4])
        merged = tracemerge.merge([plain])
        failures = tracemerge.check([plain], merged)
        assert any("zero loop attribution" in f for f in failures)
        prof = self._dump_with_profiler()
        merged = tracemerge.merge([prof])
        assert tracemerge.check([prof], merged) == []

    def test_slowest_height(self):
        d = _synthetic_dump("n0", [1, 2, 3])
        # stretch the 2→3 interval to 3 s
        for ev in d["events"]:
            if ev.get("height") == 3 or (ev["kind"] == "proposal" and ev["height"] == 4):
                ev["t_ns"] += 2_000_000_000
        merged = tracemerge.merge([d])
        assert tracemerge.slowest_height(merged) == 3

    def test_format_outputs_are_strings(self):
        d = self._dump_with_profiler()
        merged = tracemerge.merge([d])
        text = tracemerge.format_timeline(merged)
        assert "height 2" in text and "commit" in text
        att = tracemerge.format_attribution([d])
        assert "consensus=" in att
        # a dump with no profiler events is reported honestly
        assert "(no profiler events)" in tracemerge.format_attribution(
            [_synthetic_dump("bare", [1, 2, 3])]
        )


class TestInProcNet:
    async def test_four_node_net_merges_into_complete_timeline(self, tmp_path):
        """Deterministic end-to-end gate: a real 4-validator in-process
        net must produce recorder dumps that merge into a complete,
        aligned per-height chain — proposal, parts coverage, maj23 steps,
        agreeing commits — with nonzero loop attribution (the first node
        owns the process-wide spawn/GC hooks on a shared loop)."""
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node
        from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
        from tendermint_tpu.types.params import BlockParams, ConsensusParams

        pvs = sorted([MockPV() for _ in range(4)], key=lambda pv: pv.address())
        gen = GenesisDoc(
            chain_id="tracemerge-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs
            ],
            consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        )
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"tm{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.05
            # probe must tick INSIDE each ~100 ms block interval or the
            # per-block attribution has nothing to read
            cfg.instrumentation.loop_probe_interval = 0.01
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for n in nodes:
                await n.start()
            for i in range(1, 4):
                addr = (
                    f"{nodes[i].node_key.id}@"
                    f"{nodes[i].switch.transport.listen_addr}"
                )
                await nodes[0].switch.dial_peer(addr)

            async def reach(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.05)

            # let the net form and sync first: a node that joins late can
            # legitimately skip a height's Propose via vote-driven round
            # jumps, which is startup churn, not the steady state this
            # gate measures.  Dumping from a post-sync watermark excises
            # it — the same `since` polling the RPC route serves.
            await asyncio.wait_for(reach(3), 60.0)
            marks = [n.flight_recorder.snapshot()["next_seq"] for n in nodes]
            await asyncio.wait_for(reach(9), 60.0)
            dumps = []
            for i, n in enumerate(nodes):
                snap = n.flight_recorder.snapshot(since=marks[i])
                snap["node"] = f"tm{i}"
                dumps.append(snap)
        finally:
            for n in nodes:
                if n.is_running:
                    await n.stop()

        merged = tracemerge.merge(dumps)
        assert len(merged["heights"]) >= 4
        # honest clocks: causal offsets stay sub-second
        assert all(abs(o) < 1000 for o in merged["offsets_ms"])
        interior = sorted(merged["heights"])[1:-1]
        assert interior
        for h in interior:
            entry = merged["heights"][h]
            # complete per-height chain: proposal with an origin, and on
            # every node an agreeing commit
            assert entry["proposal_ms"] is not None
            assert entry["origin"] in {f"tm{i}" for i in range(4)}
            assert "hash_mismatch" not in entry
            for name in (f"tm{i}" for i in range(4)):
                v = entry["nodes"].get(name)
                assert v is not None, f"height {h}: {name} missing entirely"
                assert v.get("commit_ms") is not None
                # the first interior height can have pre-watermark step
                # entries on the fastest node; past it the maj23 landmarks
                # must be present everywhere
                if h != interior[0]:
                    assert v.get("precommit_maj23_ms") is not None
        assert merged["commit_skew_ms_p90"] is not None
        assert merged["coverage_ms_p90"] is not None
        # the full trace-net-smoke gate, attribution requirement included
        assert tracemerge.check(dumps, merged) == []
        # node0 started first → owns the process hooks → its attribution
        # is the process attribution
        by_height = tracemerge.attribution_by_height(dumps[0])
        assert by_height
        for att in by_height.values():
            shares = {k: v for k, v in att.items() if k.endswith("_pct")}
            # per-block decomposition is exhaustive: shares sum to ~100%.
            # Tolerance: a loop.busy event just inside the interval edge
            # carries busy time from its whole preceding probe interval,
            # so the sum can overshoot by ~probe/block = 10 ms/100 ms here
            # (the 100-val rig runs 1 s probes against 60 s blocks, where
            # the same slop is <2%)
            assert sum(shares.values()) == pytest.approx(100.0, abs=12.0)
            assert any(v > 0 for v in shares.values())
        # the one-line summary (median per key across heights) exists —
        # note per-KEY medians need not sum to exactly 100
        assert tracemerge.median_attribution(by_height) is not None


class TestSpoolIngest:
    """Offline forensics: load_dump reads a crash spool (the JSON-lines
    journal a SIGKILLed node leaves behind) and merges it with live RPC
    dumps — the dead node appears on the causal timeline like any other."""

    def _write_spool(self, path, node, heights):
        from tendermint_tpu.libs.tracing import FlightSpool

        rec = FlightRecorder(size=8192)
        sp = FlightSpool(str(path), rec, node=node)
        for h in heights:
            rec.record("proposal", height=h, round=0, src="self")
            for step in ("Propose", "Prevote", "Precommit", "Commit"):
                rec.record("step", height=h, round=0, step=step)
            rec.record("commit", height=h, txs=0, block=f"hash{h}")
            sp.flush()
        # no close(): the node was SIGKILLed
        return rec

    def test_load_dump_reads_spool_and_merges_with_live_dump(self, tmp_path):
        spool_path = tmp_path / "flight.spool"
        rec = self._write_spool(spool_path, "dead-node", [1, 2, 3, 4])
        d = tracemerge.load_dump(str(spool_path))
        assert d["node"] == "dead-node" and d.get("source") == "spool"
        assert len(d["events"]) == len(rec.events())
        # a live peer's snapshot of the same run (same hashes, own anchor)
        live = FlightRecorder(size=8192)
        for h in [1, 2, 3, 4, 5]:
            live.record("proposal", height=h, round=0, src="self")
            for step in ("Propose", "Prevote", "Precommit", "Commit"):
                live.record("step", height=h, round=0, step=step)
            live.record("commit", height=h, txs=0, block=f"hash{h}")
        snap = live.snapshot()
        snap["node"] = "live-node"
        merged = tracemerge.merge([d, snap])
        assert set(merged["nodes"]) == {"dead-node", "live-node"}
        shared = [h for h, e in merged["heights"].items()
                  if {"dead-node", "live-node"} <= set(e["nodes"])]
        assert len(shared) == 4
        assert merged["hash_mismatch_heights"] == []
        # the dead node's chains pass the trace gate (no attribution
        # required: its profiler died with it)
        failures = tracemerge.check([d, snap], merged, require_attribution=False)
        assert failures == []

    def test_torn_spool_still_loads(self, tmp_path):
        spool_path = tmp_path / "flight.spool"
        self._write_spool(spool_path, "torn-node", [1, 2, 3])
        import os

        size = os.path.getsize(spool_path)
        with open(spool_path, "r+b") as f:
            f.truncate(size - 9)  # kill landed mid-append
        d = tracemerge.load_dump(str(spool_path))
        assert d["node"] == "torn-node"
        assert d["torn"] == 1 and len(d["events"]) >= 3 * 6 - 1

    def test_name_override_and_non_spool_rejection(self, tmp_path):
        spool_path = tmp_path / "flight.spool"
        self._write_spool(spool_path, "x", [1])
        d = tracemerge.load_dump(str(spool_path), name="renamed")
        assert d["node"] == "renamed"
        junk = tmp_path / "junk.txt"
        junk.write_text("not json\nat all\n")
        with pytest.raises(ValueError):
            tracemerge.load_dump(str(junk))
