"""ABCI tests: types round-trip, local + socket clients, example apps,
proxy connections.

Coverage model: abci/example/example_test.go (socket round-trip),
abci/example/kvstore/kvstore_test.go, counter semantics.
"""

import asyncio
import base64

import pytest

from tendermint_tpu.abci import (
    LocalClient,
    RequestCheckTx,
    RequestDeliverTx,
    RequestEcho,
    RequestEndBlock,
    RequestInfo,
    RequestInitChain,
    RequestQuery,
    RequestSetOption,
    SocketClient,
    SocketServer,
    ValidatorUpdate,
)
from tendermint_tpu.abci.examples import CounterApplication, KVStoreApplication
from tendermint_tpu.abci.types import RequestCommit, decode_msg, encode_msg
from tendermint_tpu.libs.kvstore import MemDB
from tendermint_tpu.proxy import AppConns, default_client_creator


class TestWireTypes:
    def test_roundtrip(self):
        req = RequestCheckTx(tx=b"hello", type=1)
        d = encode_msg("check_tx", req)
        kind, decoded = decode_msg(dict(d), direction=0)
        assert kind == "check_tx" and decoded == req

    def test_nested_validator_updates(self):
        from tendermint_tpu.abci.types import ResponseEndBlock

        resp = ResponseEndBlock(validator_updates=[ValidatorUpdate("ed25519", b"\x01" * 32, 5)])
        d = encode_msg("end_block", resp)
        _, decoded = decode_msg(dict(d), direction=1)
        assert decoded.validator_updates[0].pub_key == b"\x01" * 32
        assert decoded.validator_updates[0].power == 5


class TestKVStoreApp:
    def test_deliver_and_query(self):
        app = KVStoreApplication()
        r = app.deliver_tx(RequestDeliverTx(tx=b"name=satoshi"))
        assert r.is_ok
        q = app.query(RequestQuery(data=b"name"))
        assert q.value == b"satoshi"
        missing = app.query(RequestQuery(data=b"nobody"))
        assert missing.value == b""
        c = app.commit()
        assert len(c.data) == 32
        info = app.info(RequestInfo())
        assert info.last_block_height == 1
        assert info.last_block_app_hash == c.data

    def test_state_persists_across_restart(self):
        db = MemDB()
        app = KVStoreApplication(db)
        app.deliver_tx(RequestDeliverTx(tx=b"k=v"))
        h = app.commit().data
        app2 = KVStoreApplication(db)
        assert app2.height == 1
        assert app2.app_hash == h
        assert app2.query(RequestQuery(data=b"k")).value == b"v"

    def test_validator_updates(self):
        app = KVStoreApplication()
        pk = b"\x02" * 32
        from tendermint_tpu.abci.types import RequestBeginBlock

        tx = b"val:" + base64.b64encode(pk) + b"!10"
        assert app.check_tx(RequestCheckTx(tx=tx)).is_ok
        app.begin_block(RequestBeginBlock())
        assert app.deliver_tx(RequestDeliverTx(tx=tx)).is_ok
        eb = app.end_block(RequestEndBlock(height=1))
        assert len(eb.validator_updates) == 1
        assert eb.validator_updates[0].power == 10
        # removal
        app.begin_block(RequestBeginBlock())
        app.deliver_tx(RequestDeliverTx(tx=b"val:" + base64.b64encode(pk) + b"!0"))
        assert app.validators.get(pk) is None

    def test_bad_validator_tx_rejected(self):
        app = KVStoreApplication()
        assert app.check_tx(RequestCheckTx(tx=b"val:garbage")).code != 0


class TestCounterApp:
    def test_serial_nonces(self):
        app = CounterApplication(serial=True)
        assert app.deliver_tx(RequestDeliverTx(tx=(0).to_bytes(8, "big"))).is_ok
        assert app.deliver_tx(RequestDeliverTx(tx=(1).to_bytes(8, "big"))).is_ok
        bad = app.deliver_tx(RequestDeliverTx(tx=(5).to_bytes(8, "big")))
        assert bad.code == 2
        app.commit()
        assert app.check_tx(RequestCheckTx(tx=(1).to_bytes(8, "big"))).code == 2
        assert app.check_tx(RequestCheckTx(tx=(2).to_bytes(8, "big"))).is_ok

    def test_set_option(self):
        app = CounterApplication(serial=False)
        app.set_option(RequestSetOption(key="serial", value="on"))
        assert app.serial


class TestLocalClient:
    async def test_calls(self):
        app = KVStoreApplication()
        client = LocalClient(app)
        await client.start()
        echo = await client.echo("hi")
        assert echo.message == "hi"
        r = await client.deliver_tx(RequestDeliverTx(tx=b"a=b"))
        assert r.is_ok
        c = await client.commit()
        assert len(c.data) == 32
        await client.stop()


class TestSocketClientServer:
    async def test_roundtrip_over_socket(self, tmp_path):
        sock = f"unix://{tmp_path}/abci.sock"
        app = KVStoreApplication()
        server = SocketServer(sock, app)
        await server.start()
        try:
            client = SocketClient(sock)
            await client.start()
            try:
                echo = await client.echo("ping")
                assert echo.message == "ping"
                info = await client.info(RequestInfo(version="x"))
                assert info.last_block_height == 0
                await client.init_chain(
                    RequestInitChain(
                        chain_id="c", validators=[ValidatorUpdate("ed25519", b"\x03" * 32, 7)]
                    )
                )
                assert app.validators[b"\x03" * 32] == 7
                r = await client.deliver_tx(RequestDeliverTx(tx=b"x=y"))
                assert r.is_ok
                # pipelined requests keep FIFO order
                results = await asyncio.gather(
                    *(client.deliver_tx(RequestDeliverTx(tx=b"k%d=v" % i)) for i in range(20))
                )
                assert all(r.is_ok for r in results)
                q = await client.query(RequestQuery(data=b"k7"))
                assert q.value == b"v"
                await client.flush()
                await client.stop()
            finally:
                if client.is_running:
                    await client.stop()
        finally:
            await server.stop()


class TestAppConns:
    async def test_three_connections(self):
        conns = AppConns(default_client_creator("kvstore"))
        await conns.start()
        try:
            info = await conns.query().info(RequestInfo())
            assert info.last_block_height == 0
            r = await conns.mempool().check_tx(RequestCheckTx(tx=b"a=1"))
            assert r.is_ok
            d = await conns.consensus().deliver_tx(RequestDeliverTx(tx=b"a=1"))
            assert d.is_ok
            c = await conns.consensus().commit()
            assert len(c.data) == 32
        finally:
            await conns.stop()
