"""sr25519 / ristretto255 / merlin tests (reference: crypto/sr25519).

Known-answer tests pin the primitives to public vectors: the merlin
crate's transcript equivalence vector and RFC 9496's small-multiple and
invalid-encoding vectors — cross-implementation correctness without
network access.
"""

import asyncio

import pytest

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.crypto import ristretto
from tendermint_tpu.crypto.sr25519 import Sr25519PrivKey, Sr25519PubKey
from tendermint_tpu.crypto.strobe import Strobe128, Transcript

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))


class TestMerlin:
    def test_transcript_known_answer(self):
        """The merlin crate's test_transcript_equivalence vector."""
        t = Transcript(b"test protocol")
        t.append_message(b"some label", b"some data")
        assert (
            t.challenge_bytes(b"challenge", 32).hex()
            == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
        )

    def test_transcripts_diverge_on_input(self):
        t1 = Transcript(b"proto")
        t2 = Transcript(b"proto")
        t1.append_message(b"l", b"a")
        t2.append_message(b"l", b"b")
        assert t1.challenge_bytes(b"c", 32) != t2.challenge_bytes(b"c", 32)

    def test_clone_is_independent(self):
        t = Transcript(b"proto")
        t.append_message(b"l", b"x")
        c = t.clone()
        c.append_message(b"l2", b"y")
        assert t.challenge_bytes(b"c", 16) != c.challenge_bytes(b"c", 16)

    def test_strobe_rejects_transport_ops(self):
        s = Strobe128(b"x")
        with pytest.raises(ValueError):
            s._begin_op(0x08, False)  # FLAG_T


RFC9496_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    "e882b131016b52c1d3337080187cf768423efccbb517bb495ab812c4160ff44e",
    "f64746d3c92b13050ed8d80236a7f0007c3b3f962f5ba793d19a601ebb1df403",
    "44f53520926ec81fbd5a387845beb7df85a96a24ece18738bdcfa6a7822a176d",
    "903293d8f2287ebe10e2374dc1a53e0bc887e592699f02d077d5263cdd55601c",
    "02622ace8f7303a31cafc63f8fc48fdc16e1c8c8d234b2f0d6685282a9076031",
]

RFC9496_BAD = [
    # non-canonical field elements
    "00ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff",
    "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # negative field elements
    "0100000000000000000000000000000000000000000000000000000000000080",
    "ed57ffd8c914fb201471d1c3d245ce3c746fcbe63a3679d51b6a516ebebe0e20",
    # non-square x^2
    "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371",
]


class TestRistretto:
    def test_small_multiples_of_basepoint(self):
        zero = (0, 1, 1, 0)
        for i, expected in enumerate(RFC9496_MULTIPLES):
            p = em.scalar_mult(i, ristretto.BASEPOINT) if i else zero
            assert ristretto.encode(p).hex() == expected
            decoded = ristretto.decode(bytes.fromhex(expected))
            assert decoded is not None
            assert ristretto.equals(decoded, p)

    def test_bad_encodings_rejected(self):
        for b in RFC9496_BAD:
            assert ristretto.decode(bytes.fromhex(b)) is None

    def test_encode_decode_roundtrip_random(self):
        for i in range(1, 20):
            p = em.scalar_mult(i * 104729 + 7, ristretto.BASEPOINT)
            enc = ristretto.encode(p)
            assert ristretto.encode(ristretto.decode(enc)) == enc


class TestSr25519:
    def test_expand_ed25519_known_answer(self):
        """Cross-implementation KAT: the substrate //Alice dev account.
        schnorrkel MiniSecretKey(e5be...) expanded with ExpandEd25519 (the
        mode the reference's go-schnorrkel uses, privkey.go:31) derives the
        canonical Alice public key — proving key derivation, ristretto
        encoding and scalar math agree with curve25519-dalek/schnorrkel."""
        mini = bytes.fromhex(
            "e5be9a5092b81bca64be81d212e7f2f9eba183bb7a90954f7b76361f6edb5c0a"
        )
        pub = Sr25519PrivKey(mini).pub_key().bytes()
        assert pub == bytes.fromhex(
            "d43593c715fdd31c61141abd04a99fd6822c8558854ccde39a5684e7a56da27d"
        )

    def test_default_context_is_empty(self):
        """The reference signs with NewSigningContext([]byte{}, msg)
        (pubkey.go:49) — a b'substrate' context would diverge on the wire."""
        from tendermint_tpu.crypto.sr25519 import SIGNING_CTX

        assert SIGNING_CTX == b""
        k = Sr25519PrivKey.from_secret(b"seed")
        sig = k.sign(b"m")
        assert k.pub_key().verify(b"m", sig, ctx=b"")

    def test_sign_verify(self):
        k = Sr25519PrivKey.from_secret(b"seed")
        sig = k.sign(b"hello sr25519")
        assert len(sig) == 64 and sig[63] & 0x80
        assert k.pub_key().verify(b"hello sr25519", sig)

    def test_reject_wrong_message_key_or_sig(self):
        k = Sr25519PrivKey.from_secret(b"seed")
        sig = k.sign(b"msg")
        assert not k.pub_key().verify(b"other msg", sig)
        assert not Sr25519PrivKey.generate().pub_key().verify(b"msg", sig)
        bad = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
        assert not k.pub_key().verify(b"msg", bad)
        # missing schnorrkel marker bit
        unmarked = sig[:63] + bytes([sig[63] & 0x7F])
        assert not k.pub_key().verify(b"msg", unmarked)

    def test_deterministic_and_context_separated(self):
        k = Sr25519PrivKey.from_secret(b"seed")
        assert k.sign(b"m") == k.sign(b"m")
        sig_other_ctx = k.sign(b"m", ctx=b"other-context")
        assert not k.pub_key().verify(b"m", sig_other_ctx)  # default ctx
        assert k.pub_key().verify(b"m", sig_other_ctx, ctx=b"other-context")

    def test_codec_roundtrip(self):
        from tendermint_tpu.crypto.keys import pubkey_from_dict

        k = Sr25519PrivKey.generate()
        d = k.pub_key().to_dict()
        pk2 = pubkey_from_dict(d)
        assert isinstance(pk2, Sr25519PubKey)
        assert pk2.equals(k.pub_key())
        assert len(k.pub_key().address()) == 20

    def test_multisig_threshold_over_sr25519(self):
        from tendermint_tpu.crypto.multisig import (
            MultisigThresholdPubKey,
            build_multisig_signature,
        )
        from tendermint_tpu.libs.bitarray import BitArray

        keys = [Sr25519PrivKey.from_secret(b"ms%d" % i) for i in range(4)]
        pub = MultisigThresholdPubKey(2, [k.pub_key() for k in keys])
        msg = b"threshold payload"
        bits = BitArray(4)
        bits.set_index(1, True)
        bits.set_index(3, True)
        agg = build_multisig_signature(bits, [keys[1].sign(msg), keys[3].sign(msg)])
        assert pub.verify(msg, agg)
        # below threshold fails
        bits1 = BitArray(4)
        bits1.set_index(1, True)
        assert not pub.verify(msg, build_multisig_signature(bits1, [keys[1].sign(msg)]))


class TestSr25519Consensus:
    def test_verify_commit_with_sr25519_validators(self):
        """BASELINE config #3 core: a commit signed entirely by sr25519
        validators verifies through ValidatorSet.verify_commit's
        type-routed path."""
        from tests.test_types import make_commit, make_block_id, CHAIN_ID
        from tendermint_tpu.types import MockPV, Validator, ValidatorSet

        pvs = [MockPV(priv_key=Sr25519PrivKey.from_secret(b"v%d" % i)) for i in range(6)]
        vset = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
        pvs.sort(key=lambda pv: pv.address())
        bid = make_block_id()
        commit = make_commit(vset, pvs, 3, 0, bid)
        vset.verify_commit(CHAIN_ID, bid, 3, commit)  # raises on failure

    async def test_sr25519_net_commits(self, tmp_path):
        """4 validators holding sr25519 keys reach consensus end-to-end."""
        from tests.test_consensus_net import stop_net, wait_all_height
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node
        from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

        pvs = sorted(
            [MockPV(priv_key=Sr25519PrivKey.from_secret(b"net%d" % i)) for i in range(4)],
            key=lambda pv: pv.address(),
        )
        gen = GenesisDoc(
            chain_id="sr-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"sr{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for node in nodes:
                await node.start()
            for i in range(4):
                for j in range(i + 1, 4):
                    addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
                    await nodes[i].switch.dial_peer(addr)
            await wait_all_height(nodes, 3, timeout=60.0)
            hashes = {n.block_store.load_block(2).hash() for n in nodes}
            assert len(hashes) == 1
        finally:
            await stop_net(nodes)
