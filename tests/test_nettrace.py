"""Network-plane tracing tests: wire-level trace context, cross-node
stage budgets, and the fleet telescope.

The trace-context plane rides the capability ladder one level above the
summary exchange (gossip_version >= 3) and every carried field is
attacker-suppliable, so the contracts pinned here are:

1. negotiation — frames to traced peers carry (o, ow, hp), frames to
   older peers omit them byte-for-byte and still parse on both sides;
2. monotone hops — a vote received at hop k relays at k+1, never less;
3. byzantine clamps — a forged huge hop count or far-future origin
   timestamp is clamped + counted and NEVER yields a latency sample, so
   it can't poison tracemerge's measured skew estimation;
4. net_budget / measured_offsets — the analysis layer computes the
   documented stages from synthetic recorder events;
5. telescope — the collector survives dead nodes and keeps a killed
   node's buffered window on the merged timeline;
6. hot path — record_sampled with trace stamping stays under the 5 µs
   tripwire (same budget as tests/test_tracing.py).
"""

import asyncio
import json
import time
from types import SimpleNamespace

import pytest

from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus.reactor import (
    TRACE_MAX_HOP,
    VOTE_CHANNEL,
    ConsensusReactor,
    PeerRoundState,
    _enc,
)
from tendermint_tpu.consensus.types import HeightVoteSet, RoundState
from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
from tendermint_tpu.encoding import codec
from tendermint_tpu.libs import tracemerge, tracing
from tendermint_tpu.libs.metrics import ConsensusMetrics
from tendermint_tpu.p2p.node_info import GOSSIP_TRACE_VERSION, NodeInfo
from tendermint_tpu.tools.telescope import Telescope
from tendermint_tpu.types import (
    BlockID,
    MockPV,
    Validator,
    ValidatorSet,
    Vote,
)
from tendermint_tpu.types.canonical import PREVOTE_TYPE

CHAIN_ID = "nettrace-test-chain"


# ---------------------------------------------------------------------------
# fixtures (the test_gossip.py unit-level slice)
# ---------------------------------------------------------------------------


class _HostVerifier(BatchVerifier):
    def __init__(self):
        super().__init__(min_device_batch=10**9)  # always the host path

    def start_warmup(self):
        return self  # no background compile thread in unit tests


class _FakeSwitch:
    def __init__(self, node_id="ee" * 20):
        self.node_id = node_id
        self.stopped = []

    async def stop_peer_for_error(self, peer, reason):
        self.stopped.append((peer.id, reason))


class _FakeCS:
    def __init__(self, vset, height=5):
        self.config = ConsensusConfig()
        self.rs = RoundState(
            height=height,
            validators=vset,
            votes=HeightVoteSet(CHAIN_ID, height, vset),
            last_validators=None,
        )
        self.sm_state = SimpleNamespace(chain_id=CHAIN_ID)
        self.on_new_round_step = []
        self.on_vote = []
        self.on_valid_block = []
        self.on_proposal = []
        self.on_new_block_part = []
        self.metrics = ConsensusMetrics()
        self.recorder = tracing.FlightRecorder(size=512)
        self.added = []

    async def add_vote_input(self, vote, peer_id="", verified=False):
        self.added.append((vote, peer_id, verified))


class _CapturePeer:
    def __init__(self, pid, gossip_version=GOSSIP_TRACE_VERSION):
        self.id = pid
        self.gossip_version = gossip_version
        self.sent = []

    async def send(self, chan, msg):
        d = codec.loads(msg)
        self.sent.append((chan, d.pop("k"), d, msg))
        return True


def _vset_and_votes(n=4, height=5):
    pvs = [MockPV() for _ in range(n)]
    vset = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
    pvs.sort(key=lambda pv: pv.address())
    votes = []
    for pv in pvs:
        i, _ = vset.get_by_address(pv.address())
        v = Vote(
            type=PREVOTE_TYPE, height=height, round=0, block_id=BlockID(),
            timestamp_ns=1, validator_address=pv.address(), validator_index=i,
        )
        pv.sign_vote(CHAIN_ID, v)
        votes.append(v)
    return vset, votes


def _reactor(cs, verifier=None):
    r = ConsensusReactor(cs, async_verifier=verifier)
    r.switch = _FakeSwitch()
    return r


def _hop_events(recorder):
    return [e for e in recorder.events() if e["kind"] == "gossip.hop"]


# ---------------------------------------------------------------------------
# wire-level trace context
# ---------------------------------------------------------------------------


class TestTraceNegotiation:
    def test_node_info_ladder(self):
        old = NodeInfo.from_dict({"node_id": "ab" * 20})
        assert old.gossip_version == 0
        assert GOSSIP_TRACE_VERSION == 3

    async def test_batch_to_traced_peer_is_stamped_and_to_old_peer_is_not(self):
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        reactor = _reactor(cs)
        traced = _CapturePeer("aa" * 20, gossip_version=GOSSIP_TRACE_VERSION)
        legacy = _CapturePeer("bb" * 20, gossip_version=2)
        await reactor._send_vote_batch(traced, PeerRoundState(), votes, 4)
        await reactor._send_vote_batch(legacy, PeerRoundState(), votes, 4)
        _, kind, d, _ = traced.sent[0]
        assert kind == "vote_batch"
        # own votes: no stored hop -> the stamp originates at hop 0
        assert d["hp"] == 0
        assert d["o"] == reactor._trace_origin_id() and len(d["o"]) == 16
        assert isinstance(d["ow"], int) and d["ow"] > 0
        _, kind, d2, _ = legacy.sent[0]
        assert kind == "vote_batch"
        assert "o" not in d2 and "ow" not in d2 and "hp" not in d2

    async def test_knob_off_suppresses_stamping(self):
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cs.config.gossip_trace_context = False
        reactor = _reactor(cs)
        peer = _CapturePeer("aa" * 20)
        await reactor._send_vote_batch(peer, PeerRoundState(), votes, 4)
        assert "ow" not in peer.sent[0][2]

    async def test_untraced_frame_parses_unchanged(self):
        """Frames without trace fields (an old sender) must land votes
        exactly as before and emit NO gossip.hop event."""
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        svc = AsyncBatchVerifier(_HostVerifier())
        await svc.start()
        try:
            reactor = _reactor(cs, svc)
            peer = SimpleNamespace(id="old-peer-000000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            msg = _enc("vote_batch", {"votes": [v.wire() for v in votes]})
            await reactor.receive(VOTE_CHANNEL, peer, msg)
            assert len(cs.added) == len(votes)
            assert all(verified for _, _, verified in cs.added)
            assert _hop_events(cs.recorder) == []
        finally:
            await svc.stop()


class TestHopMonotone:
    async def test_received_hop_relays_plus_one(self):
        """A batch received at hop 3 emits a gossip.hop sample and is
        relayed at hop 4 — the count never decrements along a path."""
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        svc = AsyncBatchVerifier(_HostVerifier())
        await svc.start()
        try:
            reactor = _reactor(cs, svc)
            # the sender never advertised tracing (v1) yet stamps fields:
            # receivers honour the content, not the handshake
            peer = SimpleNamespace(id="relay-peer-0000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            msg = _enc("vote_batch", {
                "votes": [v.wire() for v in votes],
                "o": "cafe" * 4, "ow": time.time_ns(), "hp": 3,
            })
            await reactor.receive(VOTE_CHANNEL, peer, msg)
            assert len(cs.added) == len(votes)
            landed = [v for v, _, _ in cs.added]
            assert all(getattr(v, "_trace_hop", None) == 3 for v in landed)
            (ev,) = _hop_events(cs.recorder)
            assert ev["frame"] == "vote_batch" and ev["hop"] == 3
            assert ev["origin"] == "cafe" * 2  # 8-char prefix
            assert "lat_ms" in ev and "clamped" not in ev
            assert ev["h"] == 5

            out = _CapturePeer("cc" * 20)
            await reactor._send_vote_batch(out, PeerRoundState(), landed, 4)
            assert out.sent[0][2]["hp"] == 4
        finally:
            await svc.stop()

    def test_hop_cap_on_relay(self):
        vset, votes = _vset_and_votes(1)
        reactor = _reactor(_FakeCS(vset))
        votes[0]._trace_hop = TRACE_MAX_HOP  # already at the ceiling
        peer = _CapturePeer("dd" * 20)
        asyncio.run(reactor._send_vote_batch(peer, PeerRoundState(), votes, 1))
        assert peer.sent[0][2]["hp"] == TRACE_MAX_HOP


class TestByzantineClamps:
    def _r(self):
        vset, _ = _vset_and_votes(1)
        return _reactor(_FakeCS(vset))

    def _peer(self):
        return SimpleNamespace(id="byzantine-peer0", gossip_version=1)

    def test_huge_hop_clamped_and_counted(self):
        r = self._r()
        hp = r._trace_recv(
            "vote", self._peer(),
            {"o": "twin-forged-origin", "ow": time.time_ns(), "hp": 1 << 20},
            5,
        )
        assert hp == TRACE_MAX_HOP
        (ev,) = _hop_events(r.cs.recorder)
        assert ev["clamped"] == 1 and "lat_ms" not in ev
        assert r.trace_clamps == 1

    def test_far_future_origin_clamped(self):
        r = self._r()
        forged = time.time_ns() + 600 * 1_000_000_000
        hp = r._trace_recv("vote", self._peer(), {"ow": forged, "hp": 0}, 5)
        assert hp == 0
        (ev,) = _hop_events(r.cs.recorder)
        assert ev["clamped"] == 1 and "lat_ms" not in ev

    def test_negative_and_bool_hops_clamped_to_zero(self):
        r = self._r()
        assert r._trace_recv("vote", self._peer(), {"ow": time.time_ns(), "hp": -7}, 5) == 0
        assert r._trace_recv("vote", self._peer(), {"ow": time.time_ns(), "hp": True}, 5) == 0
        assert all(ev["clamped"] == 1 for ev in _hop_events(r.cs.recorder))

    def test_missing_or_malformed_ow_means_no_context(self):
        r = self._r()
        assert r._trace_recv("vote", self._peer(), {"hp": 3}, 5) is None
        assert r._trace_recv("vote", self._peer(), {"ow": "yesterday"}, 5) is None
        assert r._trace_recv("vote", self._peer(), {"ow": True}, 5) is None
        assert _hop_events(r.cs.recorder) == []

    def test_non_string_origin_and_events_stay_json_safe(self):
        r = self._r()
        r._trace_recv(
            "vote", self._peer(),
            {"o": b"\xff" * 32, "ow": time.time_ns(), "hp": 1}, 5,
        )
        (ev,) = _hop_events(r.cs.recorder)
        assert ev["origin"] == ""
        json.dumps(ev)  # dump_flight_recorder must be able to serve it

    def test_clamped_sample_never_reaches_skew_estimation(self):
        """The end-to-end byzantine property: a forged frame's latency
        must not move measured_offsets, however extreme the forgery."""
        r = self._r()
        now = time.time_ns()
        for _ in range(20):  # honest direct traffic, ~zero latency
            r._trace_recv("vote", self._peer(), {"ow": now, "hp": 0}, 5)
            now = time.time_ns()
        honest = tracemerge.measured_offsets(self._two_dumps(r))[0]
        for _ in range(50):  # a flood of far-future forgeries
            r._trace_recv(
                "vote", self._peer(),
                {"ow": time.time_ns() + 599 * 10**9, "hp": 1 << 30}, 5,
            )
        forged = tracemerge.measured_offsets(self._two_dumps(r))[0]
        assert forged == honest  # byte-identical offsets: forgeries ignored

    @staticmethod
    def _two_dumps(r):
        d = r.cs.recorder.snapshot()
        peer = {
            "node": "peer", "anchor": dict(d["anchor"]),
            "events": [
                {"kind": "gossip.hop", "hop": 0, "lat_ms": 0.1, "frame": "vote",
                 "t_ns": i} for i in range(10)
            ],
        }
        return [d, peer]


# ---------------------------------------------------------------------------
# net_budget
# ---------------------------------------------------------------------------


def _synthetic_height_events(h, base_ns):
    ms = 1_000_000
    t = base_ns
    return [
        {"kind": "step", "height": h, "step": "Propose", "t_ns": t},
        {"kind": "proposal", "height": h, "t_ns": t + 1 * ms},
        {"kind": "gossip.hop", "frame": "proposal", "hop": 0, "h": h,
         "lat_ms": 5.0, "t_ns": t + 1 * ms},
        {"kind": "gossip.hop", "frame": "block_part", "hop": 1, "h": h,
         "lat_ms": 2.0, "t_ns": t + 2 * ms},
        {"kind": "block.parts_complete", "height": h, "t_ns": t + 10 * ms},
        {"kind": "step", "height": h, "step": "Prevote", "t_ns": t + 12 * ms},
        {"kind": "gossip.vote_batch_recv", "h": h, "t_ns": t + 13 * ms},
        {"kind": "step", "height": h, "step": "Precommit", "t_ns": t + 20 * ms},
        {"kind": "step", "height": h, "step": "Commit", "t_ns": t + 30 * ms},
        {"kind": "commit", "height": h, "block": "ab" * 4, "t_ns": t + 30 * ms},
    ]


class TestNetBudget:
    def test_empty_is_none(self):
        assert tracing.net_budget([]) is None

    def test_stages_from_synthetic_heights(self):
        events = []
        for i, h in enumerate((10, 11, 12)):
            events += _synthetic_height_events(h, i * 100_000_000)
        b = tracing.net_budget(events)
        assert b["blocks"] == 3 and b["heights"] == [10, 12]
        # proposal_prop is the proposal frame's measured latency
        assert b["stages"]["proposal_prop"]["p50_ms"] == 5.0
        # part_stream: earliest of proposal accept (t+1ms) and first
        # block_part hop (t+2ms) -> parts_complete (t+10ms)
        assert b["stages"]["part_stream"]["p50_ms"] == pytest.approx(9.0)
        # vote_fanin: Prevote entry (t+12ms) -> Commit entry (t+30ms)
        assert b["stages"]["vote_fanin"]["p50_ms"] == pytest.approx(18.0)
        assert b["hops"]["proposal"]["n"] == 3
        assert b["hop_lat_ms"]["block_part"]["p50"] == 2.0
        assert b["hop_lat_all_ms"]["n"] == 6  # pooled across frame kinds
        assert b["clamped"] == 0

    def test_clamped_events_counted_not_measured(self):
        events = _synthetic_height_events(7, 0)
        events.append({"kind": "gossip.hop", "frame": "vote", "hop": 64,
                       "clamped": 1, "t_ns": 999})
        b = tracing.net_budget(events)
        assert b["clamped"] == 1
        assert "vote" not in b["hops"]  # clamped sample excluded everywhere

    def test_format_is_printable(self):
        events = _synthetic_height_events(7, 0)
        text = tracing.format_net_budget(tracing.net_budget(events))
        assert "vote_fanin" in text and "all frames" in text


# ---------------------------------------------------------------------------
# tracemerge: measured skew + landmark fallback
# ---------------------------------------------------------------------------


def _dump(name, wall_offset_ns=0, events=(), anchor_mono=0):
    return {
        "node": name,
        "anchor": {"mono_ns": anchor_mono, "wall_ns": 1_000_000_000_000 + wall_offset_ns},
        "events": list(events),
    }


def _hop(lat_ms, hop=0, frame="vote_batch", t_ns=0, clamped=False):
    ev = {"kind": "gossip.hop", "frame": frame, "hop": hop,
          "lat_ms": lat_ms, "t_ns": t_ns}
    if clamped:
        ev["clamped"] = 1
        del ev["lat_ms"]
    return ev


class TestMeasuredOffsets:
    def test_median_latency_normalized_across_fleet(self):
        a = _dump("a", events=[_hop(10.0, t_ns=i) for i in range(9)])
        b = _dump("b", events=[_hop(30.0, t_ns=i) for i in range(9)])
        offsets, samples = tracemerge.measured_offsets([a, b])
        assert samples == [9, 9]
        # base = median([10, 30]) = 20 -> a is 10 ms fast, b 10 ms slow
        assert offsets == [-10_000_000, 10_000_000]

    def test_untrustworthy_samples_filtered(self):
        tainted = [
            _hop(500.0, hop=2),              # relayed: queueing, not skew
            _hop(500.0, frame="block_part"), # cached frame: stale stamp
            _hop(500.0, clamped=True),       # byzantine
            {"kind": "gossip.hop", "frame": "vote", "hop": 0, "t_ns": 0},  # no lat
        ]
        a = _dump("a", events=[_hop(10.0, t_ns=i) for i in range(9)] + tainted)
        b = _dump("b", events=[_hop(10.0, t_ns=i) for i in range(9)])
        offsets, samples = tracemerge.measured_offsets([a, b])
        assert samples == [9, 9] and offsets == [0, 0]

    def test_single_node_has_nothing_to_normalize_against(self):
        a = _dump("a", events=[_hop(10.0) for _ in range(9)])
        b = _dump("b")
        offsets, samples = tracemerge.measured_offsets([a, b])
        assert offsets == [0, 0] and samples == [9, 0]


class TestLandmarkFallback:
    def _commit(self, h, t_ns):
        return {"kind": "commit", "height": h, "block": "cd" * 4, "t_ns": t_ns}

    def _proposal(self, h, t_ns):
        return {"kind": "proposal", "height": h, "t_ns": t_ns}

    def test_fastsync_joiner_falls_back_to_proposal_landmarks(self):
        """A node whose window holds NO commits (late fastsync joiner)
        used to silently keep offset 0 — it must now align on the looser
        proposal landmark and report its sample count."""
        ms = 1_000_000
        shared = [(h, h * 100 * ms) for h in (3, 4, 5)]
        a = _dump("a", events=[self._commit(h, t) for h, t in shared]
                  + [self._proposal(h, t - 10 * ms) for h, t in shared])
        b = _dump("b", events=[self._commit(h, t) for h, t in shared]
                  + [self._proposal(h, t - 10 * ms) for h, t in shared])
        # the joiner: same proposal walls but shifted 50 ms by clock skew,
        # and no commit events at all
        skew = 50 * ms
        c = _dump("c", wall_offset_ns=skew,
                  events=[self._proposal(h, t - 10 * ms) for h, t in shared])
        offsets, samples, kinds = tracemerge.estimate_offsets([a, b, c], detail=True)
        assert kinds[:2] == ["commit", "commit"]
        assert kinds[2] == "proposal" and samples[2] == 3
        assert offsets[2] == pytest.approx(skew, abs=2 * ms)

    def test_merge_reports_sources_and_prefers_measured(self):
        ms = 1_000_000
        shared = [(h, h * 100 * ms) for h in (3, 4, 5)]
        commits = [self._commit(h, t) for h, t in shared]
        a = _dump("a", events=commits + [_hop(10.0, t_ns=i) for i in range(8)])
        b = _dump("b", events=commits + [_hop(30.0, t_ns=i) for i in range(8)])
        c = _dump("c", events=list(commits) + [_hop(20.0, t_ns=0)])  # < 8 samples
        merged = tracemerge.merge([a, b, c])
        assert merged["offset_sources"] == ["measured", "measured", "landmark:commit"]
        assert merged["offset_samples"][0] == 8 and merged["offset_samples"][2] >= 1
        assert merged["offsets_ms"][0] == pytest.approx(-10.0)
        assert merged["offsets_ms"][1] == pytest.approx(10.0)
        assert 3 in merged["heights"] and 5 in merged["heights"]
        tracemerge.format_timeline(merged)  # renders with source annotations


# ---------------------------------------------------------------------------
# telescope
# ---------------------------------------------------------------------------


class TestTelescope:
    def test_dead_target_flips_down_but_snapshot_survives(self):
        t = Telescope(["127.0.0.1:1"], interval=0.01)
        asyncio.run(t.poll_once())
        assert t.scopes[0].alive is False and t.scopes[0].failures == 1
        snap = t.snapshot()
        assert snap["fleet"]["alive"] == 0 and snap["fleet"]["total"] == 1
        json.dumps(snap)
        assert "DOWN" in t.render(snap)

    def test_killed_node_keeps_its_window_on_the_merged_timeline(self):
        """The SIGKILL acceptance property in miniature: scope b's RPC is
        gone (alive=False) but its buffered events still merge, with a
        measured-skew offset source when its samples suffice."""
        ms = 1_000_000
        shared = [(h, h * 100 * ms) for h in (3, 4, 5)]
        commits = [
            {"kind": "commit", "height": h, "block": "ef" * 4, "t_ns": t}
            for h, t in shared
        ]
        t = Telescope(["a:26657", "b:26657"], interval=0.01)
        for scope, lat in zip(t.scopes, (10.0, 30.0)):
            scope.name = scope.target[0]
            scope.anchor = {"mono_ns": 0, "wall_ns": 10**12}
            scope.events = commits + [_hop(lat, t_ns=i) for i in range(9)]
            scope.height = 5
        t.scopes[0].alive = True
        t.scopes[1].alive = False  # SIGKILLed mid-run
        snap = t.snapshot()
        assert snap["fleet"]["alive"] == 1
        assert snap["merged"]["offset_sources"] == ["measured", "measured"]
        names = [n["name"] for n in snap["nodes"]]
        assert names == ["a", "b"]
        dead = snap["nodes"][1]
        assert dead["alive"] is False and dead["events_buffered"] > 0
        assert dead["net_budget"]["hops"]  # per-node budget still computed
        out = t.render(snap)
        assert "DOWN" in out and "measured" in out
        json.dumps(snap)

    def test_window_bounds_buffer(self):
        t = Telescope(["a:26657"], window=10)
        s = t.scopes[0]
        s.anchor = {"mono_ns": 0, "wall_ns": 10**12}
        # simulate what _poll_node does on fresh events past the window
        s.events = [{"kind": "x", "t_ns": i} for i in range(25)]
        if len(s.events) > t.window:
            del s.events[: len(s.events) - t.window]
        assert len(s.events) == 10 and s.events[0]["t_ns"] == 15


# ---------------------------------------------------------------------------
# hot path
# ---------------------------------------------------------------------------


class TestTraceHotPath:
    def test_record_sampled_with_trace_fields_under_tripwire(self):
        """gossip.hop stays off the recorder hot path: stamping the full
        trace field set must hold the same <5 µs/event budget
        tests/test_tracing.py pins for bare record()."""
        r = tracing.FlightRecorder(size=4096)
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            r.record_sampled(
                "gossip.hop", frame="vote_batch", peer="ab" * 4,
                origin="cd" * 4, hop=1, h=i, lat_ms=1.234,
            )
        per_event = (time.perf_counter() - t0) / n
        assert per_event < 5e-6, f"gossip.hop record cost {per_event * 1e6:.2f}us"

    def test_sampling_knob_thins_events(self):
        r = tracing.FlightRecorder(size=4096, sample_high_rate=8)
        for i in range(64):
            r.record_sampled("gossip.hop", hop=0, h=i)
        evs = [e for e in r.events() if e["kind"] == "gossip.hop"]
        assert len(evs) == 8
        assert all(e.get("sampled") == 8 for e in evs)
