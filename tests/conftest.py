"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (real-chip execution is covered by bench.py
and the driver's dryrun).  Environment must be set before jax imports.

Every coroutine test runs under a leak guard: a test that returns while
asyncio tasks are still alive on its loop FAILS (the reference runs
leaktest on every net test — long-lived stray tasks are exactly how the
round-4 reactor-starvation bug class recurs).
"""

import os

# Force cpu even if the ambient environment points at a (tunnel-attached)
# accelerator: per-vote flush batches would pay a host<->device round trip
# per call, and compiles are minutes, not seconds, over the tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent XLA compile cache: the ed25519 ladder kernels take minutes of
# compile on a small CI host and are identical across test processes and
# reruns; cache them once per machine.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(autouse=True)
def _loopprof_hook_guard():
    """The scheduler profiler's spawn + GC hooks are process-wide
    (libs/loopprof.py); a test that crashes between a Node's start and
    stop would leak them into every later test's Service.spawn.  Restore
    a clean slate after each test."""
    yield
    import gc

    from tendermint_tpu.libs import loopprof

    prof = loopprof._ACTIVE
    if prof is not None:
        loopprof._ACTIVE = None
        if prof._gc_cb is not None and prof._gc_cb in gc.callbacks:
            gc.callbacks.remove(prof._gc_cb)


def pytest_collection_modifyitems(config, items):
    # Provide asyncio support without the pytest-asyncio plugin: run
    # coroutine tests on a fresh event loop.
    pass


def _drain_leaked_tasks(loop, leaked):
    for t in leaked:
        t.cancel()

    async def _reap():
        await asyncio.gather(*leaked, return_exceptions=True)

    loop.run_until_complete(asyncio.wait_for(_reap(), timeout=10))


def pytest_pyfunc_call(pyfuncitem):
    import inspect

    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(func(**kwargs), timeout=120))
            # leak guard: the test owns this loop, so anything still alive
            # is an un-stopped service/server/background task.  Candidates
            # get a short real drain first: a cancellation cascade mid-
            # unwind (wait_for abandons the inner future on outer cancel,
            # bpo semantics) finishes in a few cycles, while a genuinely
            # un-stopped task survives the window and is flagged.
            leaked = [t for t in asyncio.all_tasks(loop) if not t.done()]
            if leaked:
                # Progress-based drain, not one fixed window: a cancellation
                # cascade mid-unwind (peer ping/send tasks, BLS pairings
                # HOLDING the GIL on executor threads) can need seconds of
                # loop time on a saturated box, but it keeps RESOLVING tasks
                # while it does — so keep draining while the pending count
                # shrinks (hard cap 10 s) and give up only once the set
                # stops making progress for 2 s.  A genuinely un-stopped
                # task (server, ticker, routine) never progresses and is
                # flagged after the same ~2 s a quiet box always paid; a
                # loaded box no longer flakes on a cascade that merely
                # needed longer (the PEX churn-soak flake class).
                deadline = loop.time() + 10.0
                last_n, last_progress = len(leaked), loop.time()
                pending = leaked
                while pending and loop.time() < deadline:
                    loop.run_until_complete(asyncio.wait(pending, timeout=0.25))
                    pending = [t for t in pending if not t.done()]
                    now = loop.time()
                    if len(pending) < last_n:
                        last_n, last_progress = len(pending), now
                    elif now - last_progress > 2.0:
                        break  # stuck, not slow: stop extending the window
                leaked = pending
            if leaked:
                names = ", ".join(
                    f"{t.get_name()}<{getattr(t.get_coro(), '__qualname__', t.get_coro())}>"
                    for t in leaked
                )
                _drain_leaked_tasks(loop, leaked)
                pytest.fail(
                    f"leak guard: test left {len(leaked)} live asyncio task(s) "
                    f"behind: {names}",
                    pytrace=False,
                )
        finally:
            loop.close()
        return True
    return None
