"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (real-chip execution is covered by bench.py
and the driver's dryrun).  Environment must be set before jax imports.
"""

import os

# Force cpu even if the ambient environment points at a (tunnel-attached)
# accelerator: per-vote flush batches would pay a host<->device round trip
# per call, and compiles are minutes, not seconds, over the tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def pytest_collection_modifyitems(config, items):
    # Provide asyncio support without the pytest-asyncio plugin: run
    # coroutine tests on a fresh event loop.
    pass


def pytest_pyfunc_call(pyfuncitem):
    import inspect

    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(func(**kwargs), timeout=120))
        finally:
            loop.close()
        return True
    return None
