"""Prometheus metrics tests.

Reference parity: consensus/metrics.go:66, node/node.go:128 — the same
metric names under the `tendermint` namespace, scraped live from a
running net.
"""

import asyncio

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.libs.metrics import MetricsProvider
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "metrics-chain"


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


def _parse(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)
    return out


class TestProvider:
    def test_nop_provider_accepts_everything(self):
        p = MetricsProvider(False, CHAIN_ID)
        p.consensus.height.set(5)
        p.p2p.peer_receive_bytes_total.labels(chain_id="x", peer_id="y", chID="1").inc(10)
        p.mempool.tx_size_bytes.observe(100)
        assert p.exposition() == b""

    def test_prometheus_provider_registers_reference_names(self):
        p = MetricsProvider(True, CHAIN_ID)
        p.consensus.height.set(7)
        p.consensus.validators.set(4)
        p.mempool.size.set(3)
        p.p2p.peers.set(2)
        text = p.exposition().decode()
        metrics = _parse(text)
        assert metrics[f'tendermint_consensus_height{{chain_id="{CHAIN_ID}"}}'] == 7
        assert metrics[f'tendermint_consensus_validators{{chain_id="{CHAIN_ID}"}}'] == 4
        assert metrics[f'tendermint_mempool_size{{chain_id="{CHAIN_ID}"}}'] == 3
        assert metrics[f'tendermint_p2p_peers{{chain_id="{CHAIN_ID}"}}'] == 2

    def test_two_providers_do_not_collide(self):
        # the reference's global default registry would explode here
        a = MetricsProvider(True, "chain-a")
        b = MetricsProvider(True, "chain-b")
        a.consensus.height.set(1)
        b.consensus.height.set(2)
        assert b'chain-a' in a.exposition() and b'chain-b' in b.exposition()


class TestNopDriftGuard:
    def test_nop_attrs_exactly_match_prometheus_attrs(self):
        """Every metrics class must expose the SAME attribute set on its
        nop path and its prometheus path — a metric defined in only one
        of the two is silently dead (the peer_send_bytes_total bug class:
        defined, exported as 0, never incremented anywhere)."""
        import inspect

        from prometheus_client import CollectorRegistry

        import tendermint_tpu.libs.metrics as metrics_mod

        classes = [
            cls
            for name, cls in vars(metrics_mod).items()
            if inspect.isclass(cls) and name.endswith("Metrics")
        ]
        names = {cls.__name__ for cls in classes}
        assert {
            "ConsensusMetrics", "P2PMetrics", "MempoolMetrics",
            "StateMetrics", "VerifyMetrics", "LoopMetrics",
        } <= names
        for cls in classes:
            nop = cls(None, "drift-chain")
            prom = cls(CollectorRegistry(), "drift-chain")
            assert set(vars(nop)) == set(vars(prom)), (
                f"{cls.__name__}: nop/prometheus attribute drift: "
                f"{set(vars(nop)) ^ set(vars(prom))}"
            )

    def test_provider_exposes_every_subsystem(self):
        p = MetricsProvider(True, CHAIN_ID)
        for sub in ("consensus", "p2p", "mempool", "state", "verify", "loop"):
            assert getattr(p, sub) is not None

    def test_loop_family_exports_under_reference_names(self):
        # the scheduler-profiler series: histograms bound to chain_id at
        # construction (count series exist even before any observation),
        # labeled gauges resolved per category/queue at use
        p = MetricsProvider(True, CHAIN_ID)
        p.loop.lag_seconds.observe(0.003)
        p.loop.gc_pause_seconds.observe(0.001)
        p.loop.task_busy_seconds.labels(category="consensus").set(1.5)
        p.loop.queue_depth.labels(queue="cs_recv").set(42)
        metrics = _parse(p.exposition().decode())
        key = f'chain_id="{CHAIN_ID}"'
        assert metrics[f"tendermint_loop_lag_seconds_count{{{key}}}"] == 1
        assert metrics[f"tendermint_loop_gc_pause_seconds_count{{{key}}}"] == 1
        busy = [v for k, v in metrics.items()
                if k.startswith("tendermint_loop_task_busy_seconds{")
                and 'category="consensus"' in k]
        assert busy == [1.5]
        depth = [v for k, v in metrics.items()
                 if k.startswith("tendermint_loop_queue_depth{")
                 and 'queue="cs_recv"' in k]
        assert depth == [42]


class TestMetricsServer:
    async def test_stop_is_idempotent_and_content_type_versioned(self):
        from tendermint_tpu.libs.metrics import MetricsServer

        provider = MetricsProvider(True, CHAIN_ID)
        srv = MetricsServer(provider, "127.0.0.1:0")
        await srv.start()
        try:
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{srv.bound_addr}/metrics") as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"] == (
                        "text/plain; version=0.0.4; charset=utf-8"
                    )
        finally:
            await srv.stop()
        await srv.stop()  # second stop must be a no-op, not a crash

    async def test_bind_failure_names_the_configured_address(self):
        import pytest

        from tendermint_tpu.libs.metrics import MetricsServer

        provider = MetricsProvider(True, CHAIN_ID)
        first = MetricsServer(provider, "127.0.0.1:0")
        await first.start()
        try:
            addr = first.bound_addr
            second = MetricsServer(MetricsProvider(True, CHAIN_ID), addr)
            with pytest.raises(OSError, match=addr.replace(".", r"\.")):
                await second.start()
        finally:
            await first.stop()


class TestLiveScrape:
    async def test_scrape_running_net(self, tmp_path):
        """Two-validator net, node0 serving /metrics with the verify
        engine ON: height advances, peers gauge is live, verify-subsystem
        series populated, send-bytes counted, flight-recorder span chains
        complete and monotonic."""
        pvs = sorted([MockPV() for _ in range(2)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"m{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.05
            # scheduler-profiler probe must tick inside the short run so
            # the loop series and loop.* recorder events populate
            cfg.instrumentation.loop_probe_interval = 0.02
            if i == 0:
                cfg.instrumentation.prometheus = True
                cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
                # the engine on: its vote-ingress batcher and metrics are
                # what this scrape asserts (tiny batches ride the host
                # path inside the engine — no device compile stall)
                cfg.tpu.enabled = True
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for n in nodes:
                await n.start()
            addr = f"{nodes[1].node_key.id}@{nodes[1].switch.transport.listen_addr}"
            await nodes[0].switch.dial_peer(addr)

            async def reach(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(reach(3), 60.0)

            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://{nodes[0].metrics_server.bound_addr}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
            metrics = _parse(text)
            key = f'chain_id="{CHAIN_ID}"'
            assert metrics[f"tendermint_consensus_height{{{key}}}"] >= 3
            assert metrics[f"tendermint_consensus_validators{{{key}}}"] == 2
            assert metrics[f"tendermint_consensus_validators_power{{{key}}}"] == 20
            assert metrics[f"tendermint_p2p_peers{{{key}}}"] == 1
            assert f"tendermint_mempool_size{{{key}}}" in metrics
            # block interval gauge observed a commit (reference: Gauge,
            # consensus/metrics.go:46 — exact series name preserved)
            assert metrics[f"tendermint_consensus_block_interval_seconds{{{key}}}"] >= 0
            # counters keep the reference names (no _total suffix)
            assert f"tendermint_mempool_failed_txs{{{key}}}" in metrics
            assert f"tendermint_mempool_recheck_times{{{key}}}" in metrics

            # event-driven gossip series: wakeups fired, vote batches and
            # part bursts were sent (both peers advertise the batched wire)
            assert metrics[f"tendermint_consensus_gossip_wakeups{{{key}}}"] > 0
            assert metrics[f"tendermint_consensus_vote_batch_size_count{{{key}}}"] > 0
            assert metrics[f"tendermint_consensus_parts_per_burst_count{{{key}}}"] > 0

            # verify subsystem: the vote-ingress batcher flushed real
            # batches, so the histograms observed and the quantum gauge is live
            assert metrics[f"tendermint_verify_batch_size_count{{{key}}}"] > 0
            assert metrics[f"tendermint_verify_queue_wait_seconds_count{{{key}}}"] > 0
            assert f"tendermint_verify_flush_quantum_seconds{{{key}}}" in metrics
            assert metrics[f"tendermint_verify_backend_tier{{{key}}}"] in (1, 2, 3)

            # evidence pool observability: the series exist on every node
            # (the pool was invisible before) — a clean run exports 0
            assert metrics[f"tendermint_evidence_pending{{{key}}}"] == 0
            assert metrics[f"tendermint_evidence_committed_total{{{key}}}"] == 0
            # chaos family registered (populated only under fault injection)
            assert metrics[f"tendermint_chaos_links_degraded{{{key}}}"] == 0
            assert f"tendermint_chaos_msgs_dropped_total{{{key}}}" in metrics

            # send-side byte accounting mirrors the receive side: gossip to
            # the peer must have produced nonzero send-bytes series
            sent = sum(
                v for k, v in metrics.items()
                if k.startswith("tendermint_p2p_peer_send_bytes_total{")
            )
            assert sent > 0, "peer_send_bytes_total never incremented"

            # scheduler-profiler family: the lag probe observed, per-
            # category busy gauges are live (node0 owns the process hooks
            # — it started first), and the choke-point queues are sampled
            assert metrics[f"tendermint_loop_lag_seconds_count{{{key}}}"] > 0
            assert f"tendermint_loop_gc_pause_seconds_count{{{key}}}" in metrics
            busy = {
                k: v for k, v in metrics.items()
                if k.startswith("tendermint_loop_task_busy_seconds{")
            }
            assert any(v > 0 for v in busy.values()), f"no busy category live: {busy}"
            depths = {
                k: v for k, v in metrics.items()
                if k.startswith("tendermint_loop_queue_depth{")
            }
            for q in ("cs_recv", "verify_pending", "flush_executor", "mconn_send"):
                assert any(f'queue="{q}"' in k for k in depths), (
                    f"queue probe {q} never sampled: {sorted(depths)}"
                )

            # flight recorder via the RPC route: complete, monotonic span
            # chains for the committed heights
            from tendermint_tpu.libs import tracing
            from tendermint_tpu.rpc.core import RPCCore

            snap = await RPCCore(nodes[0]).call("dump_flight_recorder")
            assert snap["enabled"] is True
            ts = [e["t_ns"] for e in snap["events"]]
            assert ts == sorted(ts), "recorder events not monotonic"
            chains = tracing.step_chains(snap["events"])
            complete = tracing.complete_heights(chains)
            assert len(complete) >= 2, f"no complete span chains: {chains}"
            assert any(e["kind"] == "verify.flush" for e in snap["events"])

            # cross-node tracing surface survives the RPC round-trip:
            # the monotonic→wall anchor, the node label, and the new
            # provenance fields on proposal/commit/gossip events
            assert set(snap["anchor"]) == {"mono_ns", "wall_ns"}
            assert abs(snap["anchor"]["wall_ns"] - __import__("time").time_ns()) < 60e9
            assert snap["node"] == nodes[0].config.base.moniker
            props = [e for e in snap["events"] if e["kind"] == "proposal"]
            assert props, "no proposal events recorded"
            peer_prefix = nodes[1].node_key.id[:8]
            assert all(e["src"] in ("self", peer_prefix) for e in props)
            assert {e["src"] for e in props} == {"self", peer_prefix}, (
                "expected both self-born and relayed proposals in a 2-val net"
            )
            commits = [e for e in snap["events"] if e["kind"] == "commit"]
            assert commits and all(
                isinstance(e["block"], str) and len(e["block"]) == 12 for e in commits
            )
            recvs = [e for e in snap["events"] if e["kind"] == "gossip.vote_batch_recv"]
            assert recvs, "no vote batches received"
            assert all(e["peer"] == peer_prefix and e["dup"] >= 0 for e in recvs)
            # scheduler-profiler events ride the same dump
            loop_kinds = {e["kind"] for e in snap["events"] if e["kind"].startswith("loop.")}
            assert {"loop.lag", "loop.busy", "loop.queue"} <= loop_kinds, loop_kinds

            # kinds prefix filtering through the RPC route (string form)
            filt = await RPCCore(nodes[0]).call(
                "dump_flight_recorder", {"kinds": "step,commit"}
            )
            assert filt["events"] and all(
                e["kind"] in ("step", "commit") for e in filt["events"]
            )
        finally:
            for n in nodes:
                if n.is_running:
                    await n.stop()
