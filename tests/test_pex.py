"""PEX + address book tests.

Reference parity: p2p/pex/addrbook_test.go (add/select/promote/persist),
p2p/pex/pex_reactor_test.go (request/response, unsolicited punishment,
bootstrap-from-seed net convergence).
"""

import asyncio

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.pex import AddrBook
from tendermint_tpu.p2p.pex.addrbook import NEW_BUCKET_SIZE
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "pex-chain"


def mk_addr(i: int, port: int = 26656) -> str:
    return f"{'%040x' % i}@10.0.{i % 250}.{i // 250}:{port}"


class TestAddrBook:
    def test_add_pick_and_selection(self, tmp_path):
        book = AddrBook(strict=False)
        for i in range(1, 50):
            assert book.add_address(mk_addr(i), src=mk_addr(1000).split("@")[0])
        assert book.size() == 49
        addr = book.pick_address()
        assert addr is not None and "@" in addr
        sel = book.get_selection()
        assert 1 <= len(sel) <= 250
        assert all("@" in a for a in sel)

    def test_rejects_self_and_duplicates_capped(self, tmp_path):
        my_id = "%040x" % 7
        book = AddrBook(strict=False, our_ids={my_id})
        assert not book.add_address(f"{my_id}@1.2.3.4:26656")
        assert book.add_address(mk_addr(1))
        # re-adding is idempotent at same bucket
        assert book.add_address(mk_addr(1))
        assert book.size() == 1

    def test_mark_good_promotes_to_old(self, tmp_path):
        book = AddrBook(strict=False)
        a = mk_addr(3)
        book.add_address(a, src="src")
        pid = a.split("@")[0]
        assert not book.addrs[pid].is_old()
        book.mark_good(pid)
        assert book.addrs[pid].is_old()
        # old addresses are not re-bucketed into new by a later add
        assert not book.add_address(a, src="other")
        assert book.addrs[pid].is_old()

    def test_mark_bad_removes(self, tmp_path):
        book = AddrBook(strict=False)
        a = mk_addr(4)
        book.add_address(a)
        book.mark_bad(a)
        assert book.size() == 0

    def test_bad_addresses_not_picked(self, tmp_path):
        book = AddrBook(strict=False)
        a = mk_addr(5)
        book.add_address(a)
        pid = a.split("@")[0]
        ka = book.addrs[pid]
        ka.attempts = 5
        ka.last_attempt = 1.0  # long ago
        assert book.pick_address() is None

    def test_bucket_eviction_bounds_size(self, tmp_path):
        book = AddrBook(strict=False)
        # same source group → same bucket; must cap at NEW_BUCKET_SIZE
        for i in range(1, NEW_BUCKET_SIZE + 20):
            book.add_address(f"{'%040x' % i}@10.0.0.1:{10000 + i}", src="onesrc")
        assert all(len(b) <= NEW_BUCKET_SIZE for b in book.new_buckets)

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        book = AddrBook(path, strict=False)
        for i in range(1, 20):
            book.add_address(mk_addr(i), src="s")
        book.mark_good(mk_addr(3).split("@")[0])
        book.save()
        book2 = AddrBook(path, strict=False)
        assert book2.size() == book.size()
        assert book2.addrs[mk_addr(3).split("@")[0]].is_old()
        assert book2.pick_address() is not None


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


class TestPEXBootstrap:
    async def test_net_bootstraps_from_single_seed(self, tmp_path):
        """4 validators, NO persistent_peers: nodes 1-3 know only the seed
        (node 0).  PEX discovery must mesh the net and consensus commit
        blocks — the open-network bootstrap the round-4 verdict called the
        #1 missing component."""
        import tendermint_tpu.p2p.pex.pex_reactor as pexmod

        pvs = sorted([MockPV() for _ in range(4)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"pex{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.p2p.addr_book_strict = False
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        # speed discovery up for the test
        orig_fast = pexmod.FAST_ENSURE_INTERVAL
        pexmod.FAST_ENSURE_INTERVAL = 0.2
        try:
            await nodes[0].start()
            seed_addr = f"{nodes[0].node_key.id}@{nodes[0].switch.transport.listen_addr}"
            for i in (1, 2, 3):
                nodes[i].config.p2p.seeds = seed_addr
                await nodes[i].start()

            async def meshed():
                while not all(n.switch.num_peers() >= 3 for n in nodes):
                    await asyncio.sleep(0.1)

            await asyncio.wait_for(meshed(), 60.0)
            # discovery also filled the books
            assert all(n.addr_book.size() >= 3 for n in nodes)

            async def committed(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.1)

            await asyncio.wait_for(committed(2), 60.0)
            hashes = {n.block_store.load_block(1).hash() for n in nodes}
            assert len(hashes) == 1
        finally:
            pexmod.FAST_ENSURE_INTERVAL = orig_fast
            for n in nodes:
                if n.is_running:
                    await n.stop()

    async def test_churn_soak_third_of_net_restarts_and_reconverges(self, tmp_path):
        """PEX soak under churn (ROADMAP carried item): kill a third of a
        PEX-discovered net, restart the victims on FRESH ports (durable stores,
        same node keys), repeat — after every cycle the survivors must
        re-mesh with the returnees, consensus must resume committing past
        the pre-churn tip, and the victims' trust scores (decayed by the
        survivors' failed dials while they were down) must recover once
        outbound dials succeed again."""
        import tendermint_tpu.p2p.pex.pex_reactor as pexmod

        N, VICTIMS = 6, [4, 5]  # a third of the net
        pvs = sorted([MockPV() for _ in range(N)], key=lambda pv: pv.address())
        gen = _gen(pvs)

        def mk_node(i):
            cfg = make_test_cfg(str(tmp_path / f"churn{i}"))
            cfg.rpc.laddr = ""
            # DURABLE stores: a restarted validator must resume from its
            # committed height — wiping a live validator's state re-signs
            # old heights, which is self-equivocation and a (reference-
            # correct) CONSENSUS FAILURE, not a churn scenario
            cfg.base.db_backend = "sqlite"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.p2p.addr_book_strict = False
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            return Node(cfg, gen, priv_validator=pvs[i])

        nodes = [mk_node(i) for i in range(N)]
        orig_fast = pexmod.FAST_ENSURE_INTERVAL
        pexmod.FAST_ENSURE_INTERVAL = 0.2
        try:
            await nodes[0].start()
            seed_addr = f"{nodes[0].node_key.id}@{nodes[0].switch.transport.listen_addr}"
            for i in range(1, N):
                nodes[i].config.p2p.seeds = seed_addr
                await nodes[i].start()

            async def meshed(min_peers=3):
                while not all(
                    n.switch.num_peers() >= min_peers for n in nodes if n.is_running
                ):
                    await asyncio.sleep(0.1)

            async def committed(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.1)

            await asyncio.wait_for(meshed(), 60.0)
            await asyncio.wait_for(committed(2), 60.0)

            for cycle in range(2):
                tip = max(n.block_store.height() for n in nodes)
                victim_ids = [nodes[i].node_key.id for i in VICTIMS]
                for i in VICTIMS:
                    await nodes[i].stop()
                # survivors notice and their dials fail: trust must decay
                await asyncio.sleep(0.5)
                book = nodes[1].addr_book
                for vid in victim_ids:
                    for _ in range(6):  # the switch's dial-failure feed
                        book.mark_failed(vid)
                decayed = {vid: book.trust_value(vid) for vid in victim_ids}
                assert all(v < 1.0 for v in decayed.values())

                # restart on fresh ports (same keys, stores resume)
                for i in VICTIMS:
                    nodes[i] = mk_node(i)
                    nodes[i].config.p2p.seeds = seed_addr
                    await nodes[i].start()
                # deterministic outbound re-dial from the survivor whose
                # trust we assert on (PEX would get here on its own tick)
                for i in VICTIMS:
                    addr = (
                        f"{nodes[i].node_key.id}@"
                        f"{nodes[i].switch.transport.listen_addr}"
                    )
                    assert await nodes[1].switch.dial_peer(addr) is not None

                await asyncio.wait_for(meshed(), 60.0)
                # consensus resumes past the pre-churn tip with ALL nodes
                # (returnees resume from their stored height and catch up)
                await asyncio.wait_for(committed(tip + 2), 90.0)
                # dial success fed mark_good: trust recovers.  Polled, not
                # point-sampled — PEX may still be re-dialing the victim's
                # STALE pre-restart address in this window (mark_failed
                # races the recovery), and the metric's idle-interval
                # neutral entries need bucket rollovers to lift the score.
                async def recovered():
                    while any(
                        book.trust_value(vid) <= decayed[vid] for vid in victim_ids
                    ):
                        await asyncio.sleep(0.5)
                await asyncio.wait_for(recovered(), 45.0)

            h = min(n.block_store.height() for n in nodes) - 1
            hashes = {n.block_store.load_block(h).hash() for n in nodes}
            assert len(hashes) == 1, f"net diverged at height {h}"
        finally:
            pexmod.FAST_ENSURE_INTERVAL = orig_fast
            for n in nodes:
                if n.is_running:
                    await n.stop()

    async def test_unsolicited_pex_response_punished(self, tmp_path):
        from tendermint_tpu.encoding import codec
        from tendermint_tpu.p2p.pex import PEX_CHANNEL

        pvs = sorted([MockPV() for _ in range(2)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(str(tmp_path / f"up{i}"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.p2p.addr_book_strict = False
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for n in nodes:
                await n.start()
            addr = f"{nodes[1].node_key.id}@{nodes[1].switch.transport.listen_addr}"
            await nodes[0].switch.dial_peer(addr)
            await asyncio.sleep(0.2)
            # make the scenario deterministic: node1 has no request in
            # flight to node0 and won't issue one during the window
            import time as _time

            nodes[1].pex_reactor._requests_sent.discard(nodes[0].node_key.id)
            nodes[1].pex_reactor._last_request_to[nodes[0].node_key.id] = _time.monotonic()
            # node0 sends an address dump node1 never asked for
            peer = nodes[0].switch.peers[nodes[1].node_key.id]
            evil = [mk_addr(i) for i in range(1, 10)]
            await peer.send(PEX_CHANNEL, codec.dumps({"t": "pex_addrs", "addrs": evil}))

            async def dropped():
                while nodes[0].node_key.id in nodes[1].switch.peers:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(dropped(), 10.0)
            # none of the poison addresses entered node1's book
            assert all(not nodes[1].addr_book.has_address(a) for a in evil)
        finally:
            for n in nodes:
                if n.is_running:
                    await n.stop()
