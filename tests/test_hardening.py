"""Round-5 hardening tests: connection fuzzing soak, debug/profiler RPC,
switch policies (dup-IP, peer filters, unconditional peers), mempool WAL,
VoteSetBits catchup gossip.

Reference parity: p2p/fuzz.go:14, rpc/core/routes.go:48-56,
p2p/transport.go:376 + switch.go:69, mempool/clist_mempool.go:137,
consensus/reactor.go:258+738.
"""

import asyncio

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "hardening-chain"


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


def _mk_cfg(tmp_path, name):
    cfg = make_test_cfg(str(tmp_path / name))
    cfg.rpc.laddr = ""
    cfg.base.db_backend = "memdb"
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.allow_duplicate_ip = True  # localhost meshes share 127.0.0.1
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_commit = 0.1
    return cfg


async def _mesh(nodes, persistent=False):
    for i in range(len(nodes)):
        for j in range(i + 1, len(nodes)):
            addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
            await nodes[i].switch.dial_peer(addr, persistent=persistent)


async def _stop(nodes):
    for n in nodes:
        if n.is_running:
            await n.stop()


class TestFuzzSoak:
    async def test_net_commits_through_lossy_links(self, tmp_path):
        """4-validator net with 10% packet loss + up to 20 ms jitter on
        every mconn packet still reaches height 3 — gossip retransmission
        absorbs the loss (p2p/fuzz.go soak flavor)."""
        pvs = sorted([MockPV() for _ in range(4)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = _mk_cfg(tmp_path, f"fz{i}")
            cfg.p2p.test_fuzz = True
            cfg.p2p.test_fuzz_prob_drop = 0.10
            cfg.p2p.test_fuzz_max_delay = 0.02
            nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
        try:
            for n in nodes:
                await n.start()
            await _mesh(nodes, persistent=True)
            # the chaos layer is actually installed
            assert all(
                getattr(p, "fuzz", None) is not None
                for n in nodes
                for p in n.switch.peer_list()
            )

            async def all_at(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.1)

            await asyncio.wait_for(all_at(3), 90.0)
            for h in range(1, 4):
                assert len({n.block_store.load_block(h).hash() for n in nodes}) == 1
            dropped = sum(
                p.fuzz.dropped_sends + p.fuzz.dropped_recvs
                for n in nodes
                for p in n.switch.peer_list()
                if getattr(p, "fuzz", None) is not None
            )
            assert dropped > 0, "fuzz layer never dropped a message"
        finally:
            await _stop(nodes)


class TestDebugSurface:
    async def test_profiler_and_task_dump_routes(self, tmp_path):
        from tendermint_tpu.rpc.core import RPCCore
        from tendermint_tpu.rpc.jsonrpc import RPCError

        pv = MockPV()
        cfg = _mk_cfg(tmp_path, "dbg")
        cfg.p2p.laddr = ""
        node = Node(cfg, _gen([pv]), priv_validator=pv, db_backend="memdb")
        try:
            await node.start()
            core = RPCCore(node, unsafe=True)
            prof_file = str(tmp_path / "cpu.prof")
            await core.call("unsafe_start_cpu_profiler", {"filename": prof_file})
            with pytest.raises(RPCError):  # double start refused
                await core.call("unsafe_start_cpu_profiler", {})
            await asyncio.sleep(0.2)
            res = await core.call("unsafe_stop_cpu_profiler", {})
            assert res["filename"] == prof_file
            import pstats

            stats = pstats.Stats(prof_file)  # loadable pstats dump
            assert stats.total_calls >= 0

            dump = await core.call("unsafe_dump_tasks", {})
            assert dump["n_tasks"] > 0
            assert any("receive" in t["name"] or t["stack"] for t in dump["tasks"])

            # gated off without rpc.unsafe
            gated = RPCCore(node, unsafe=False)
            with pytest.raises(RPCError):
                await gated.call("unsafe_dump_tasks", {})
        finally:
            await node.stop()


class TestSwitchPolicies:
    async def test_duplicate_ip_rejected_and_unconditional_bypasses(self, tmp_path):
        pvs = sorted([MockPV() for _ in range(3)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        # node0 enforces no-dup-IP; nodes 1+2 both dial from 127.0.0.1
        cfgs = [_mk_cfg(tmp_path, f"dup{i}") for i in range(3)]
        cfgs[0].p2p.allow_duplicate_ip = False
        nodes = [
            Node(cfg, gen, priv_validator=pv, db_backend="memdb")
            for cfg, pv in zip(cfgs, pvs)
        ]
        try:
            for n in nodes:
                await n.start()
            addr0 = f"{nodes[0].node_key.id}@{nodes[0].switch.transport.listen_addr}"
            p1 = await nodes[1].switch.dial_peer(addr0)
            assert p1 is not None
            await asyncio.sleep(0.1)
            await nodes[2].switch.dial_peer(addr0)
            await asyncio.sleep(0.3)
            # second same-IP inbound was rejected by node0
            assert nodes[2].node_key.id not in nodes[0].switch.peers
            # now allow node2 as unconditional: it must get in despite dup IP
            nodes[0].switch.unconditional_peer_ids.add(nodes[2].node_key.id)
            await nodes[2].switch.dial_peer(addr0)

            async def joined():
                while nodes[2].node_key.id not in nodes[0].switch.peers:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(joined(), 10.0)
        finally:
            await _stop(nodes)

    async def test_peer_filter_rejects(self, tmp_path):
        pvs = sorted([MockPV() for _ in range(2)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = [
            Node(_mk_cfg(tmp_path, f"pf{i}"), gen, priv_validator=pv, db_backend="memdb")
            for i, pv in enumerate(pvs)
        ]
        try:
            for n in nodes:
                await n.start()
            banned = nodes[1].node_key.id
            nodes[0].switch.peer_filters.append(
                lambda ni, conn: "banned" if ni.node_id == banned else None
            )
            addr0 = f"{nodes[0].node_key.id}@{nodes[0].switch.transport.listen_addr}"
            await nodes[1].switch.dial_peer(addr0)
            await asyncio.sleep(0.3)
            assert banned not in nodes[0].switch.peers
        finally:
            await _stop(nodes)


class TestMempoolWAL:
    async def test_accepted_txs_journaled(self, tmp_path):
        from tendermint_tpu.abci.examples import KVStoreApplication
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.proxy import local_client_creator

        client = local_client_creator(KVStoreApplication())()
        await client.start()
        mp = Mempool(client, {})
        mp.init_wal(str(tmp_path / "mwal"))
        try:
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"binary\nwith=newline")
            with pytest.raises(Exception):
                await mp.check_tx(b"a=1")  # cache dup: NOT journaled again
        finally:
            txs_before_close = mp.wal_txs()
            mp.close_wal()
            await client.stop()
        assert txs_before_close == [b"a=1", b"binary\nwith=newline"]
        # the on-disk journal is crc-framed (libs/autofile frames), so
        # replay survives torn tails AND mid-file bit-rot
        from tendermint_tpu.libs import autofile

        raw = open(tmp_path / "mwal" / "wal", "rb").read()
        records = [d for k, _, d in autofile.walk_frames(raw) if k == "record"]
        assert records == [b"a=1", b"binary\nwith=newline"]


class TestVoteSetBitsCatchup:
    async def test_maj23_claim_gets_bits_response(self, tmp_path):
        """reactor.go:258/738 — a peer claiming a +2/3 majority receives
        our VoteSetBits for that (height, round, type, block_id)."""
        from tendermint_tpu.consensus.reactor import (
            STATE_CHANNEL,
            VOTE_SET_BITS_CHANNEL,
            _enc,
        )
        from tendermint_tpu.encoding import codec

        pvs = sorted([MockPV() for _ in range(2)], key=lambda pv: pv.address())
        gen = _gen(pvs)
        nodes = [
            Node(_mk_cfg(tmp_path, f"vsb{i}"), gen, priv_validator=pv, db_backend="memdb")
            for i, pv in enumerate(pvs)
        ]
        try:
            for n in nodes:
                await n.start()
            await _mesh(nodes)

            async def running():
                while not all(n.block_store.height() >= 1 for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(running(), 30.0)
            # freeze progress: lengthen the commit pause at runtime so the
            # claimed (height, round) is still current when the maj23
            # message lands
            for n in nodes:
                n.consensus.config.timeout_commit = 60.0
            stable_h = nodes[0].consensus.rs.height
            for _ in range(100):
                await asyncio.sleep(0.1)
                h = nodes[0].consensus.rs.height
                if h == stable_h:
                    break
                stable_h = h

            # intercept node1's VoteSetBits channel traffic
            got_bits = asyncio.Event()
            reactor1 = nodes[1].consensus_reactor
            orig_receive = reactor1.receive

            async def spy(chan_id, peer, msg_bytes):
                if chan_id == VOTE_SET_BITS_CHANNEL:
                    msg = codec.loads(msg_bytes)
                    if msg.get("k") == "vote_set_bits":
                        got_bits.set()
                await orig_receive(chan_id, peer, msg_bytes)

            reactor1.receive = spy
            nodes[1].switch.reactors_by_ch[VOTE_SET_BITS_CHANNEL] = type(
                "R", (), {"receive": staticmethod(spy)}
            )()

            # node1 claims a maj23 for node0's current height/round; node0
            # must answer with vote_set_bits (reactor.go:258)
            rs = nodes[0].consensus.rs
            peer0 = nodes[1].switch.peers[nodes[0].node_key.id]
            prevotes = rs.votes.prevotes(rs.round) if rs.votes else None
            bid = nodes[0].block_store.load_block_meta(1).block_id
            await peer0.send(
                STATE_CHANNEL,
                _enc("vote_set_maj23", {
                    "height": rs.height, "round": rs.round, "type": 1,
                    "block_id": bid.to_dict(),
                }),
            )
            await asyncio.wait_for(got_bits.wait(), 15.0)
        finally:
            await _stop(nodes)
