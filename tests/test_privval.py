"""privval tests: FilePV double-sign protection + remote signer socket.

Reference parity: privval/file_test.go (sign/re-sign/regression cases),
privval/signer_client_test.go.  The crash-safety test is the VERDICT #4
criterion: state persists BEFORE the signature escapes, so killing the
process after signing but before any other durable write cannot lead to a
conflicting re-sign after restart.
"""

import asyncio
import time

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.privval import FilePV, SignerClient, SignerServer
from tendermint_tpu.privval.file import (
    STEP_PRECOMMIT,
    STEP_PREVOTE,
    DoubleSignError,
    FilePVLastSignState,
)
from tendermint_tpu.types import BlockID, GenesisDoc, GenesisValidator, PartSetHeader, Vote
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.proposal import Proposal

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN = "pv-chain"


def mk_vote(pv, h=5, r=0, t=PREVOTE_TYPE, blk=b"\x01" * 32, ts=None):
    return Vote(
        type=t,
        height=h,
        round=r,
        block_id=BlockID(blk, PartSetHeader(1, b"\x02" * 32)) if blk else BlockID(),
        timestamp_ns=ts if ts is not None else time.time_ns(),
        validator_address=pv.address(),
        validator_index=0,
    )


class TestFilePV:
    def _pv(self, tmp_path):
        return FilePV.load_or_generate(
            str(tmp_path / "pv_key.json"), str(tmp_path / "pv_state.json")
        )

    def test_gen_save_load_roundtrip(self, tmp_path):
        pv = self._pv(tmp_path)
        pv2 = FilePV.load(str(tmp_path / "pv_key.json"), str(tmp_path / "pv_state.json"))
        assert pv2.address() == pv.address()
        assert pv2.get_pub_key().bytes() == pv.get_pub_key().bytes()

    def test_sign_vote_persists_and_verifies(self, tmp_path):
        pv = self._pv(tmp_path)
        v = mk_vote(pv)
        pv.sign_vote(CHAIN, v)
        assert pv.get_pub_key().verify(v.sign_bytes(CHAIN), v.signature)
        lss = FilePVLastSignState.load(str(tmp_path / "pv_state.json"))
        assert (lss.height, lss.round, lss.step) == (5, 0, STEP_PREVOTE)
        assert lss.signature == v.signature

    def test_identical_resign_returns_same_signature(self, tmp_path):
        pv = self._pv(tmp_path)
        v = mk_vote(pv, ts=1234)
        pv.sign_vote(CHAIN, v)
        sig1 = v.signature
        v2 = mk_vote(pv, ts=1234)
        pv.sign_vote(CHAIN, v2)
        assert v2.signature == sig1

    def test_timestamp_only_diff_reuses_signature(self, tmp_path):
        """privval/file.go:296 — same vote, newer timestamp: release the
        previously signed timestamp + signature, do not sign fresh bytes."""
        pv = self._pv(tmp_path)
        v = mk_vote(pv, ts=1_000)
        pv.sign_vote(CHAIN, v)
        v2 = mk_vote(pv, ts=2_000)
        pv.sign_vote(CHAIN, v2)
        assert v2.timestamp_ns == 1_000
        assert v2.signature == v.signature

    def test_conflicting_same_hrs_refused(self, tmp_path):
        pv = self._pv(tmp_path)
        pv.sign_vote(CHAIN, mk_vote(pv, blk=b"\x01" * 32))
        with pytest.raises(DoubleSignError):
            pv.sign_vote(CHAIN, mk_vote(pv, blk=b"\x03" * 32))

    def test_hrs_regression_refused(self, tmp_path):
        pv = self._pv(tmp_path)
        pv.sign_vote(CHAIN, mk_vote(pv, h=5, r=2, t=PRECOMMIT_TYPE))
        with pytest.raises(DoubleSignError):  # height regression
            pv.sign_vote(CHAIN, mk_vote(pv, h=4, r=2))
        with pytest.raises(DoubleSignError):  # round regression
            pv.sign_vote(CHAIN, mk_vote(pv, h=5, r=1))
        with pytest.raises(DoubleSignError):  # step regression (precommit->prevote)
            pv.sign_vote(CHAIN, mk_vote(pv, h=5, r=2, t=PREVOTE_TYPE))

    def test_step_order_allows_forward_progress(self, tmp_path):
        pv = self._pv(tmp_path)
        p = Proposal(height=5, round=0, block_id=BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32)), timestamp_ns=1)
        pv.sign_proposal(CHAIN, p)
        pv.sign_vote(CHAIN, mk_vote(pv, h=5, r=0, t=PREVOTE_TYPE))
        pv.sign_vote(CHAIN, mk_vote(pv, h=5, r=0, t=PRECOMMIT_TYPE))
        pv.sign_vote(CHAIN, mk_vote(pv, h=6, r=0, t=PREVOTE_TYPE))

    def test_kill_after_sign_no_double_sign_on_restart(self, tmp_path):
        """Sign, then 'crash' before any WAL write: a fresh process loading
        the same state file must refuse a conflicting same-HRS sign and
        must reproduce the identical signature for the same request."""
        pv = self._pv(tmp_path)
        v = mk_vote(pv, ts=777, blk=b"\x01" * 32)
        pv.sign_vote(CHAIN, v)

        # restart: state reloaded from disk only
        pv2 = FilePV.load(str(tmp_path / "pv_key.json"), str(tmp_path / "pv_state.json"))
        conflicting = mk_vote(pv2, ts=999, blk=b"\x0f" * 32)
        with pytest.raises(DoubleSignError):
            pv2.sign_vote(CHAIN, conflicting)
        same = mk_vote(pv2, ts=777, blk=b"\x01" * 32)
        pv2.sign_vote(CHAIN, same)
        assert same.signature == v.signature

    def test_state_file_is_atomic(self, tmp_path):
        pv = self._pv(tmp_path)
        for h in range(1, 30):
            pv.sign_vote(CHAIN, mk_vote(pv, h=h))
            lss = FilePVLastSignState.load(str(tmp_path / "pv_state.json"))
            assert lss.height == h


class TestRemoteSigner:
    async def test_sign_over_socket(self, tmp_path):
        file_pv = FilePV.load_or_generate(
            str(tmp_path / "k.json"), str(tmp_path / "s.json")
        )
        client = SignerClient("127.0.0.1:0", accept_timeout=10.0)
        # start listener without blocking on accept: run start concurrently
        start_task = asyncio.ensure_future(client.start())
        await asyncio.sleep(0.05)
        server = SignerServer(client.listen_addr, file_pv)
        await server.start()
        await start_task
        try:
            assert client.get_pub_key().bytes() == file_pv.get_pub_key().bytes()
            v = mk_vote(file_pv)
            await client.sign_vote(CHAIN, v)
            assert file_pv.get_pub_key().verify(v.sign_bytes(CHAIN), v.signature)
            # double-sign refusal crosses the socket as an error
            from tendermint_tpu.privval.signer import RemoteSignerError

            with pytest.raises(RemoteSignerError):
                await client.sign_vote(CHAIN, mk_vote(file_pv, blk=b"\x0c" * 32))
        finally:
            await server.stop()
            await client.stop()

    async def test_tcp_channel_is_encrypted(self, tmp_path):
        """tcp privval runs over SecretConnection (socket_listeners.go:80):
        sign-bytes must never appear in plaintext on the wire."""
        file_pv = FilePV.load_or_generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
        client = SignerClient("127.0.0.1:0", accept_timeout=10.0)
        start_task = asyncio.ensure_future(client.start())
        await asyncio.sleep(0.05)
        server = SignerServer(client.listen_addr, file_pv)
        await server.start()
        await start_task
        try:
            assert client._conn._sc is not None  # SecretConnection active
            assert server._chan._sc is not None
        finally:
            await server.stop()
            await client.stop()

    async def test_reconnect_with_different_key_rejected(self, tmp_path):
        """An attacker who can reach priv_validator_laddr must not be able
        to replace the established signer with their own key."""
        real_pv = FilePV.load_or_generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
        client = SignerClient("127.0.0.1:0", accept_timeout=10.0, timeout=2.0)
        start_task = asyncio.ensure_future(client.start())
        await asyncio.sleep(0.05)
        server = SignerServer(client.listen_addr, real_pv)
        await server.start()
        await start_task
        attacker_pv = FilePV.load_or_generate(
            str(tmp_path / "ak.json"), str(tmp_path / "as.json")
        )
        attacker = SignerServer(client.listen_addr, attacker_pv)

        # also: an attacker CLAIMING the victim's pubkey (it is public!)
        # must fail the proof-of-possession challenge
        class _ClaimingPV:
            def get_pub_key(self):
                return real_pv.get_pub_key()  # stated, not possessed

            def sign_challenge(self, nonce):
                return b"\x00" * 64  # cannot actually sign

            def sign_vote(self, chain_id, vote):
                vote.signature = b"\x00" * 64

            def sign_proposal(self, chain_id, proposal):
                proposal.signature = b"\x00" * 64

        claiming = SignerServer(client.listen_addr, _ClaimingPV())
        try:
            await attacker.start()
            await claiming.start()
            await asyncio.sleep(0.3)  # give the probes time to run + reject
            # the original signer still serves; signing still uses the real key
            v = mk_vote(real_pv)
            await client.sign_vote(CHAIN, v)
            assert real_pv.get_pub_key().verify(v.sign_bytes(CHAIN), v.signature)
            assert client.get_pub_key().bytes() == real_pv.get_pub_key().bytes()
        finally:
            await attacker.stop()
            await claiming.stop()
            await server.stop()
            await client.stop()

    async def test_unix_socket_roundtrip(self, tmp_path):
        file_pv = FilePV.load_or_generate(str(tmp_path / "k.json"), str(tmp_path / "s.json"))
        sock = str(tmp_path / "pv.sock")
        client = SignerClient(f"unix://{sock}", accept_timeout=10.0)
        start_task = asyncio.ensure_future(client.start())
        await asyncio.sleep(0.05)
        server = SignerServer(f"unix://{sock}", file_pv)
        await server.start()
        await start_task
        try:
            v = mk_vote(file_pv)
            await client.sign_vote(CHAIN, v)
            assert file_pv.get_pub_key().verify(v.sign_bytes(CHAIN), v.signature)
        finally:
            await server.stop()
            await client.stop()

    async def test_node_runs_with_remote_signer(self, tmp_path):
        """Solo validator produces blocks with signing delegated over the
        privval socket (the node/node.go:612 configuration)."""
        file_pv = FilePV.load_or_generate(
            str(tmp_path / "k.json"), str(tmp_path / "s.json")
        )
        gen = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(file_pv.address(), file_pv.get_pub_key(), 10)],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        client = SignerClient("127.0.0.1:0", accept_timeout=10.0)
        start_task = asyncio.ensure_future(client.start())
        await asyncio.sleep(0.05)
        server = SignerServer(client.listen_addr, file_pv)
        await server.start()
        await start_task

        cfg = make_test_cfg(str(tmp_path / "rsnode"))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "memdb"
        node = Node(cfg, gen, priv_validator=client, db_backend="memdb")
        try:
            await node.start()

            async def reach(h):
                while node.block_store.height() < h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(3), 30.0)
            # blocks were signed by the remote key
            commit = node.block_store.load_block_commit(2)
            assert commit.signatures[0].validator_address == file_pv.address()
        finally:
            await node.stop()
            await server.stop()
