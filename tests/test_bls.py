"""BLS12-381 subsystem tests (crypto/bls + aggregate commits).

Known-answer tests pin the RFC 9380 machinery (expand_message_xmd §K.1)
and the standard compressed generator encodings; everything above rides
property tests (bilinearity via pairing_check, sign/verify, aggregation,
PoP, rogue-key demonstration) because the suite's SvdW map — chosen so
every constant derives from the curve equation (see hash_to_curve.py) —
has no published end-to-end vectors.  The JAX tier is differentially
pinned against the pure-python fold.

Integration tiers: AggregateCommit fold/verify/round-trip, genesis PoP
enforcement, privval signing domain, and in-proc nets (uniform-BLS net
must store aggregate commits + serve consensus-path catchup; mixed
ed25519+BLS set must commit with aggregation cleanly disabled).
"""

import asyncio

import pytest

from tendermint_tpu.crypto.bls import BlsPrivKey, BlsPubKey, curve, scheme
from tendermint_tpu.crypto.bls.hash_to_curve import expand_message_xmd, hash_to_g2
from tendermint_tpu.types import (
    AggregateCommit,
    AggregateLastCommit,
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    Validator,
    ValidatorSet,
    commit_from_dict,
    fold_commit,
    set_is_uniform_bls,
)
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP
from tests.test_types import CHAIN_ID, make_block_id, make_commit, signed_vote

_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))


def bls_pv(tag: bytes) -> MockPV:
    return MockPV(priv_key=BlsPrivKey.from_secret(tag))


# ---------------------------------------------------------------------------
# reference tier: known answers + properties
# ---------------------------------------------------------------------------


class TestReferenceTier:
    def test_expand_message_xmd_rfc9380_vectors(self):
        """RFC 9380 §K.1 (SHA-256, len_in_bytes=0x20) — the DST-agnostic
        core every hash_to_field call rides."""
        dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
        vectors = [
            (b"", "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
            (b"abc", "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
            (b"abcdef0123456789",
             "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1"),
            (b"q128_" + b"q" * 128,
             "b23a1d2b4d97b2ef7785562a7e8bac7eed54ed6e97e29aa51bfe3f12ddad1ff9"),
            (b"a512_" + b"a" * 512,
             "4623227bcc01293b8c130bf771da8c298dede7383243dc0993d2d94823958c4c"),
        ]
        for msg, want in vectors:
            assert expand_message_xmd(msg, dst, 0x20).hex() == want

    def test_generator_compressed_encodings(self):
        """The ZCash-serialization generator constants every BLS12-381
        implementation shares — pins compression AND the coordinate
        system in one shot."""
        assert curve.g1_compress(curve.G1_GEN).hex() == (
            "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
            "6c55e83ff97a1aeffb3af00adb22c6bb"
        )
        assert curve.g2_compress(curve.G2_GEN).hex() == (
            "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
            "334cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051"
            "c6e47ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8"
        )

    def test_point_compression_roundtrip_and_rejection(self):
        p = curve.g1_mul(curve.G1_GEN, 0xDEADBEEF)
        assert curve.g1_eq(curve.g1_decompress(curve.g1_compress(p)), p)
        q = curve.g2_mul(curve.G2_GEN, 0xC0FFEE)
        assert curve.g2_eq(curve.g2_decompress(curve.g2_compress(q)), q)
        # not-on-curve / garbage encodings must decode to None
        assert curve.g1_decompress(b"\x99" + b"\x00" * 47) is None
        assert curve.g2_decompress(b"\x99" + b"\x00" * 95) is None

    def test_hash_to_g2_in_subgroup_and_deterministic(self):
        a = hash_to_g2(b"consensus msg", scheme.DST_SIG)
        b = hash_to_g2(b"consensus msg", scheme.DST_SIG)
        assert curve.g2_eq(a, b)
        assert curve.g2_in_subgroup(a)
        assert curve.g2_in_subgroup_slow(a)  # fast ψ-check vs by-definition
        c = hash_to_g2(b"consensus msg", scheme.DST_POP)
        assert not curve.g2_eq(a, c)  # DST domain separation

    def test_pairing_bilinearity(self):
        """e(aP, Q) · e(-P, aQ) == 1 — the identity every verify rides."""
        from tendermint_tpu.crypto.bls import pairing

        a = 0x1234567
        p, q = curve.G1_GEN, curve.G2_GEN
        assert pairing.pairing_check(
            [(curve.g1_mul(p, a), q), (curve.g1_neg(p), curve.g2_mul(q, a))]
        )
        assert not pairing.pairing_check(
            [(curve.g1_mul(p, a), q), (curve.g1_neg(p), curve.g2_mul(q, a + 1))]
        )

    def test_keygen_deterministic_and_in_range(self):
        from tendermint_tpu.crypto.bls.fields import R

        sk1 = scheme.keygen(b"\x42" * 32)
        sk2 = scheme.keygen(b"\x42" * 32)
        assert sk1 == sk2 and 0 < sk1 < R
        assert scheme.keygen(b"\x43" * 32) != sk1
        with pytest.raises(ValueError):
            scheme.keygen(b"short")


class TestScheme:
    def test_sign_verify_and_rejection(self):
        sk = BlsPrivKey.from_secret(b"alpha")
        pk = sk.pub_key()
        sig = sk.sign(b"msg")
        assert pk.verify(b"msg", sig)
        assert not pk.verify(b"other", sig)
        assert not pk.verify(b"msg", sig[:-1] + bytes([sig[-1] ^ 1]))
        assert not pk.verify(b"msg", b"\x00" * 96)
        other = BlsPrivKey.from_secret(b"beta").pub_key()
        assert not other.verify(b"msg", sig)

    def test_fast_aggregate_verify(self):
        sks = [BlsPrivKey.from_secret(b"agg%d" % i) for i in range(4)]
        msg = b"the one aggregated message"
        agg = scheme.aggregate_signatures([sk.sign(msg) for sk in sks])
        pks = [sk.pub_key().bytes() for sk in sks]
        assert scheme.fast_aggregate_verify(pks, msg, agg)
        assert not scheme.fast_aggregate_verify(pks, b"other", agg)
        assert not scheme.fast_aggregate_verify(pks[:-1], msg, agg)  # missing signer
        assert not scheme.fast_aggregate_verify([], msg, agg)

    def test_batch_verify_aggregates_attributes_the_liar(self):
        sks = [BlsPrivKey.from_secret(b"batch%d" % i) for i in range(3)]
        msg = b"m"
        pks = [sk.pub_key().bytes() for sk in sks]
        good = scheme.aggregate_signatures([sk.sign(msg) for sk in sks])
        bad = scheme.aggregate_signatures([sk.sign(b"forged") for sk in sks])
        res = scheme.batch_verify_aggregates(
            [(pks, msg, good), (pks, msg, bad), (pks, msg, good)]
        )
        assert res == [True, False, True]
        # memo serves repeats without re-pairing (same claims, same result)
        assert scheme.memo_get(pks, msg, good) is True
        assert scheme.memo_get(pks, msg, bad) is False

    def test_infinity_aggregate_pubkey_rejected_in_both_lanes(self):
        """A signer subset whose secret keys sum to 0 mod r yields an
        infinity aggregate pubkey — e(INF, H(m)) == 1 for ANY message, so
        with an infinity signature every claim would 'verify'.  verify()
        guards this; the batch lane must agree (its memo feeds the strict
        synchronous path, so a divergent True would be laundered in)."""
        from tendermint_tpu.crypto.bls.fields import R

        a = 0x1234_5678_9ABC
        pks = [scheme.sk_to_pk(a), scheme.sk_to_pk(R - a)]
        inf_sig = curve.g2_compress(curve.G2_INF)
        msg = b"anything at all"
        assert not scheme.fast_aggregate_verify(pks, msg, inf_sig)
        scheme._memo.clear()
        assert scheme.batch_verify_aggregates([(pks, msg, inf_sig)]) == [False]
        assert scheme.memo_get(pks, msg, inf_sig) is False

    def test_pop_prove_verify(self):
        sk = BlsPrivKey.from_secret(b"pop")
        assert sk.pub_key().verify_pop(sk.pop())
        assert not sk.pub_key().verify_pop(b"\x01" * 96)
        other = BlsPrivKey.from_secret(b"not-pop")
        assert not sk.pub_key().verify_pop(other.pop())
        assert scheme.batch_pop_verify(
            [(sk.pub_key().bytes(), sk.pop()), (other.pub_key().bytes(), other.pop())]
        )
        assert not scheme.batch_pop_verify(
            [(sk.pub_key().bytes(), other.pop())]
        )

    def test_rogue_key_attack_works_without_pop(self):
        """The attack PoP exists to stop: pk_mal = pk_rogue − pk_victim
        lets the attacker forge an 'aggregate' of {victim, mal} alone.
        FastAggregateVerify ACCEPTS it — which is exactly why genesis
        refuses BLS validators without a valid proof of possession (the
        attacker cannot produce one for pk_mal: its secret key is
        unknown)."""
        victim = BlsPrivKey.from_secret(b"victim")
        rogue_sk = scheme.keygen(b"\x66" * 32)
        rogue_pk = curve.g1_mul(curve.G1_GEN, rogue_sk)
        mal = curve.g1_compress(
            curve.g1_add(rogue_pk, curve.g1_neg(curve.g1_decompress(victim.pub_key().bytes())))
        )
        msg = b"forged block"
        forged_agg = scheme.sign(rogue_sk, msg)
        assert scheme.fast_aggregate_verify(
            [victim.pub_key().bytes(), mal], msg, forged_agg
        )  # the scheme alone is forgeable — PoP is load-bearing


# ---------------------------------------------------------------------------
# JAX tier: differential agreement with the pure fold
# ---------------------------------------------------------------------------


class TestJaxTier:
    def test_g1_aggregation_matches_pure_fold(self):
        from tendermint_tpu.crypto.bls import jax_tier

        if not jax_tier.available():
            pytest.skip("jax not importable")
        import random

        rng = random.Random(11)
        pts = [curve.g1_mul(curve.G1_GEN, rng.randrange(1, 1 << 220)) for _ in range(3)]
        acc = curve.G1_INF
        for p in pts:
            acc = curve.g1_add(acc, p)
        out = jax_tier.aggregate_g1(pts)
        assert out is not None
        assert curve.g1_compress(out) == curve.g1_compress(acc)

    @pytest.mark.slow
    def test_g1_g2_aggregation_random_batches(self):
        from tendermint_tpu.crypto.bls import jax_tier

        if not jax_tier.available():
            pytest.skip("jax not importable")
        import random

        rng = random.Random(7)
        for n in (2, 5, 9):
            pts = [curve.g1_mul(curve.G1_GEN, rng.randrange(1, 1 << 250)) for _ in range(n)]
            acc = curve.G1_INF
            for p in pts:
                acc = curve.g1_add(acc, p)
            out = jax_tier.aggregate_g1(pts)
            assert out is not None and curve.g1_compress(out) == curve.g1_compress(acc)
        for n in (2, 6):
            pts = [curve.g2_mul(curve.G2_GEN, rng.randrange(1, 1 << 250)) for _ in range(n)]
            acc = curve.G2_INF
            for p in pts:
                acc = curve.g2_add(acc, p)
            out = jax_tier.aggregate_g2(pts)
            assert out is not None and curve.g2_compress(out) == curve.g2_compress(acc)


class TestVerifyMetricsCoverage:
    async def test_bls_agg_lane_populates_tendermint_verify_series(self):
        """`tendermint_verify_*` coverage for the new scheme: the engine's
        aggregate lane observes `bls_agg_seconds` and counts
        `bls_agg_checks` (the nop-vs-prometheus drift guard in
        test_metrics.py covers the attribute pair; this proves the lane
        actually feeds them)."""
        prometheus_client = pytest.importorskip("prometheus_client")
        from tendermint_tpu.crypto.batch_verifier import (
            AsyncBatchVerifier,
            BatchVerifier,
        )
        from tendermint_tpu.libs.metrics import VerifyMetrics

        reg = prometheus_client.CollectorRegistry()
        bv = BatchVerifier(metrics=VerifyMetrics(reg, "bls-metrics-chain"))
        abv = AsyncBatchVerifier(verifier=bv)
        await abv.start()
        try:
            ks = [BlsPrivKey.from_secret(b"vm%d" % i) for i in range(3)]
            msg = b"metrics coverage msg"
            agg = scheme.aggregate_signatures([k.sign(msg) for k in ks])
            pks = [k.pub_key().bytes() for k in ks]
            scheme._memo.clear()  # force the pairing, not a memo hit
            assert await abv.verify_bls_aggregates([(pks, msg, agg)]) == [True]
        finally:
            await abv.stop()
        labels = {"chain_id": "bls-metrics-chain"}
        assert reg.get_sample_value(
            "tendermint_verify_bls_agg_seconds_count", labels
        ) == 1
        assert reg.get_sample_value(
            "tendermint_verify_bls_agg_checks_total", labels
        ) == 1


# ---------------------------------------------------------------------------
# aggregate commits
# ---------------------------------------------------------------------------


def bls_val_set(n: int, tag: bytes = b"av"):
    pvs = sorted(
        [bls_pv(tag + b"%d" % i) for i in range(n)], key=lambda pv: pv.address()
    )
    return ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs]), pvs


class TestAggregateCommit:
    def test_fold_verify_roundtrip(self):
        vset, pvs = bls_val_set(4)
        bid = make_block_id()
        commit = make_commit(vset, pvs, 3, 0, bid)
        agg = fold_commit(commit, vset, CHAIN_ID)
        assert isinstance(agg, AggregateCommit)
        assert agg.signers.count() == 4
        # O(1) size: one 96B signature + bitmap, not 4 × (sig + ts + addr)
        assert len(agg.encode()) < len(b"".join(cs.signature for cs in commit.signatures)) + 100
        vset.verify_commit(CHAIN_ID, bid, 3, agg)  # raises on failure
        again = commit_from_dict(agg.to_dict())
        assert isinstance(again, AggregateCommit)
        vset.verify_commit(CHAIN_ID, bid, 3, again)
        # classic commits still decode through the same dispatcher
        assert isinstance(commit_from_dict(commit.to_dict()), Commit)

    def test_forged_aggregate_rejected(self):
        vset, pvs = bls_val_set(4)
        bid = make_block_id()
        agg = fold_commit(make_commit(vset, pvs, 3, 0, bid), vset, CHAIN_ID)
        bad = AggregateCommit(
            agg.height, agg.round, agg.block_id, agg.signers,
            agg.agg_sig[:-1] + bytes([agg.agg_sig[-1] ^ 1]), agg.timestamp_ns,
        )
        with pytest.raises(ValueError):
            vset.verify_commit(CHAIN_ID, bid, 3, bad)
        # bitmap below +2/3 is rejected by the power tally even when the
        # signature is VALID for the claimed (smaller) signer set
        from tendermint_tpu.libs.bitarray import BitArray
        from tendermint_tpu.types.validator import NotEnoughVotingPowerError

        two = BitArray(4)
        two.set_index(0, True)
        two.set_index(1, True)
        msg = agg.sign_message(CHAIN_ID)
        sub_sigs = []
        for pv in pvs:
            i, _ = vset.get_by_address(pv.address())
            if i in (0, 1):
                sub_sigs.append(pv.priv_key.sign(msg))
        partial = AggregateCommit(
            3, 0, bid, two, scheme.aggregate_signatures(sub_sigs), agg.timestamp_ns
        )
        with pytest.raises(NotEnoughVotingPowerError):
            vset.verify_commit(CHAIN_ID, bid, 3, partial)

    def test_mixed_set_does_not_fold(self):
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        bls = [bls_pv(b"mx%d" % i) for i in range(2)]
        eds = [MockPV(priv_key=Ed25519PrivKey.generate()) for _ in range(2)]
        pvs = sorted(bls + eds, key=lambda pv: pv.address())
        vset = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
        assert not set_is_uniform_bls(vset)
        bid = make_block_id()
        commit = make_commit(vset, pvs, 3, 0, bid)
        assert fold_commit(commit, vset, CHAIN_ID) is None
        # ...and the per-scheme routed classic verify still passes
        vset.verify_commit(CHAIN_ID, bid, 3, commit)

    def test_nil_precommits_stay_out_of_the_bitmap(self):
        from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
        from tendermint_tpu.types.vote_set import VoteSet

        vset, pvs = bls_val_set(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 3, 0, PRECOMMIT_TYPE, vset)
        for pv in pvs[:3]:
            vs.add_vote(signed_vote(pv, vset, PRECOMMIT_TYPE, 3, 0, bid))
        vs.add_vote(signed_vote(pvs[3], vset, PRECOMMIT_TYPE, 3, 0, BlockID()))  # nil
        agg = fold_commit(vs.make_commit(), vset, CHAIN_ID)
        assert agg.signers.count() == 3
        vset.verify_commit(CHAIN_ID, bid, 3, agg)

    def test_minority_aggregate_raises_power_error_and_catchup_drops_it(self):
        """A genuine-but-minority aggregate (2/4 signers: valid pairing,
        sub-2/3 power) raises NotEnoughVotingPowerError — which is NOT a
        ValueError.  The consensus catchup handler must swallow it like
        any other invalid peer frame; before the fix it escaped to the
        receive loop and killed the node as a CONSENSUS FAILURE (remote
        halt via one malicious frame)."""
        from types import SimpleNamespace

        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.libs.bitarray import BitArray
        from tendermint_tpu.types.validator import NotEnoughVotingPowerError

        vset, pvs = bls_val_set(4, tag=b"min")
        bid = make_block_id()
        signers = BitArray(4)
        signers.set_index(0, True)
        signers.set_index(1, True)
        agg = AggregateCommit(5, 0, bid, signers, b"\x00" * 96, 1)
        msg = agg.sign_message(CHAIN_ID)
        agg.agg_sig = scheme.aggregate_signatures(
            [pvs[i].priv_key.sign(msg) for i in (0, 1)]
        )
        with pytest.raises(NotEnoughVotingPowerError):
            vset.verify_commit(CHAIN_ID, bid, 5, agg)

        cs = ConsensusState.__new__(ConsensusState)
        cs.rs = SimpleNamespace(height=5, validators=vset)
        cs.block_store = SimpleNamespace(height=lambda: 0)
        cs.sm_state = SimpleNamespace(chain_id=CHAIN_ID)
        cs.log = SimpleNamespace(debug=lambda *a, **k: None)
        # must return silently (frame dropped), not raise
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(cs._apply_aggregate_commit(agg, "malicious-peer"))
        finally:
            loop.close()

    def test_trusting_verify_with_commit_vals(self):
        vset, pvs = bls_val_set(7)
        bid = make_block_id()
        agg = fold_commit(make_commit(vset, pvs, 9, 0, bid), vset, CHAIN_ID)
        vset.verify_commit_trusting(CHAIN_ID, bid, 9, agg, commit_vals=vset)
        with pytest.raises(ValueError):
            # the bitmap indexes the commit's own set; trusting-verify
            # without it cannot be sound
            vset.verify_commit_trusting(CHAIN_ID, bid, 9, agg)

    def test_median_time_is_the_fold_time_median(self):
        from tendermint_tpu.state.state import median_time

        vset, pvs = bls_val_set(4)
        bid = make_block_id()
        commit = make_commit(vset, pvs, 3, 0, bid)
        agg = fold_commit(commit, vset, CHAIN_ID)
        assert median_time(agg, vset) == agg.timestamp_ns
        assert agg.timestamp_ns == median_time(commit, vset)

    def test_sign_domain_separation(self):
        """Timestamp-free canonical bytes can never collide with the
        timestamped layout — a BLS vote signature cannot be replayed as a
        reference-domain signature or vice versa."""
        from tendermint_tpu.types import canonical

        bid = make_block_id()
        for ts in (0, 1, 123456789):
            with_ts = canonical.canonical_vote_sign_bytes(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, 5, 0, bid.hash,
                bid.parts_header.total, bid.parts_header.hash, ts,
            )
            without = canonical.canonical_vote_sign_bytes_no_ts(
                CHAIN_ID, canonical.PRECOMMIT_TYPE, 5, 0, bid.hash,
                bid.parts_header.total, bid.parts_header.hash,
            )
            assert with_ts != without

    def test_bls_double_sign_evidence_verifies(self):
        from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
        from tendermint_tpu.types.evidence import DuplicateVoteEvidence

        vset, pvs = bls_val_set(4)
        pv = pvs[0]
        a = signed_vote(pv, vset, PRECOMMIT_TYPE, 3, 0, make_block_id(b"\x01"))
        b = signed_vote(pv, vset, PRECOMMIT_TYPE, 3, 0, make_block_id(b"\x02"))
        ev = DuplicateVoteEvidence.from_votes(pv.get_pub_key(), a, b)
        ev.verify(CHAIN_ID, pv.get_pub_key())  # raises on failure

    def test_aggregate_last_commit_surface(self):
        vset, pvs = bls_val_set(4)
        bid = make_block_id()
        agg = fold_commit(make_commit(vset, pvs, 3, 0, bid), vset, CHAIN_ID)
        alc = AggregateLastCommit(agg)
        assert alc.has_two_thirds_majority()
        assert alc.two_thirds_majority()[0] == bid
        assert alc.make_commit() is agg
        assert alc.add_vote(None) is False
        assert alc.missing_votes(None) == []


# ---------------------------------------------------------------------------
# genesis / privval / config plumbing
# ---------------------------------------------------------------------------


class TestKeyPlumbing:
    def test_genesis_pop_enforced(self):
        sk = BlsPrivKey.from_secret(b"gen")
        ok = GenesisDoc(
            chain_id="bls-chain",
            validators=[GenesisValidator(b"", sk.pub_key(), 10, pop=sk.pop())],
        )
        ok.validate_and_complete()
        # round-trip keeps the PoP
        again = GenesisDoc.from_json(ok.to_json())
        assert again.validators[0].pop == sk.pop()
        missing = GenesisDoc(
            chain_id="bls-chain",
            validators=[GenesisValidator(b"", sk.pub_key(), 10)],
        )
        with pytest.raises(ValueError, match="proof of possession"):
            missing.validate_and_complete()
        forged = GenesisDoc(
            chain_id="bls-chain",
            validators=[GenesisValidator(b"", sk.pub_key(), 10, pop=b"\x01" * 96)],
        )
        with pytest.raises(ValueError, match="invalid BLS proof"):
            forged.validate_and_complete()

    def test_ed25519_genesis_needs_no_pop(self):
        from tendermint_tpu.crypto.keys import Ed25519PrivKey

        pk = Ed25519PrivKey.generate().pub_key()
        doc = GenesisDoc(chain_id="ed", validators=[GenesisValidator(b"", pk, 10)])
        doc.validate_and_complete()  # must not demand a PoP

    def test_filepv_bls_roundtrip_and_resign(self, tmp_path):
        from tendermint_tpu.privval.file import FilePV
        from tendermint_tpu.types import Vote

        key_file = str(tmp_path / "pv_key.json")
        state_file = str(tmp_path / "pv_state.json")
        pv = FilePV.generate(key_file, state_file, key_type="bls12381")
        pv.save()
        again = FilePV.load(key_file, state_file)
        assert isinstance(again.key.priv_key, BlsPrivKey)
        assert again.address() == pv.address()
        bid = make_block_id()
        from tendermint_tpu.types.canonical import PRECOMMIT_TYPE

        vote = Vote(
            type=PRECOMMIT_TYPE, height=1, round=0, block_id=bid,
            timestamp_ns=1_000, validator_address=pv.address(), validator_index=0,
        )
        again.sign_vote(CHAIN_ID, vote)
        assert pv.get_pub_key().verify(
            vote.sign_bytes_for_key(CHAIN_ID, pv.get_pub_key()), vote.signature
        )
        # same-HRS re-sign with a different timestamp short-circuits on
        # byte equality (the BLS domain has no timestamp to differ by)
        vote2 = Vote(
            type=PRECOMMIT_TYPE, height=1, round=0, block_id=bid,
            timestamp_ns=2_000, validator_address=pv.address(), validator_index=0,
        )
        again.sign_vote(CHAIN_ID, vote2)
        assert vote2.signature == vote.signature

    def test_generate_priv_key_all_types(self):
        from tendermint_tpu.crypto.keys import KEY_TYPES, generate_priv_key

        for kt in KEY_TYPES:
            priv = generate_priv_key(kt)
            pk = priv.pub_key()
            assert len(pk.address()) == 20
            sig = priv.sign(b"m")
            assert pk.verify(b"m", sig)
        with pytest.raises(ValueError):
            generate_priv_key("rsa4096")

    def test_config_rejects_unknown_key_type(self):
        from tendermint_tpu.config import Config

        cfg = Config(home="/tmp/x")
        cfg.base.key_type = "rot13"
        with pytest.raises(ValueError, match="key_type"):
            cfg.validate_basic()


# ---------------------------------------------------------------------------
# in-proc nets: uniform BLS (aggregate commits + catchup) and mixed set
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _bls_stop_budget():
    """Stop budget for BLS nets: ONLY the pure fallback tier needs one.
    A pure-tier vote verify is a ~0.5 s pairing on an executor thread that
    HOLDS the GIL, so an orderly service stop (node AND its subservices)
    can overrun the default 10 s under full-suite load and the forced stop
    leaks subservice tasks to the conftest leak guard.  The C tier drops
    the GIL for the ~3 ms ctypes pairing, so the default budget holds —
    asserted explicitly by test_bls_net_orderly_stop_within_default_budget
    below.  Kept as the documented accommodation for toolchain-less hosts."""
    from tendermint_tpu.crypto.bls import scheme
    from tendermint_tpu.libs.service import Service

    if scheme.active_tier() == "c":
        yield
        return
    old = Service.STOP_TIMEOUT
    Service.STOP_TIMEOUT = 30.0
    yield
    Service.STOP_TIMEOUT = old


def _bls_node(cfg, gen, **kw):
    from tendermint_tpu.node import Node

    return Node(cfg, gen, **kw)


def _net_cfg(make_test_cfg, home: str):
    cfg = make_test_cfg(home)
    cfg.rpc.laddr = ""
    cfg.base.db_backend = "memdb"
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_commit = 0.1
    # reference-tier pairing is ~120 ms/verify: timeouts must sit above
    # proposal/vote verify latency (same model as `testnet --key-type
    # bls12381 --fast`)
    cfg.consensus.timeout_propose = 2.0
    cfg.consensus.timeout_prevote = 0.5
    cfg.consensus.timeout_precommit = 0.5
    return cfg


class TestBlsNets:
    async def test_bls_net_commits_aggregate_and_serves_catchup(self, tmp_path):
        """4 BLS validators: every stored commit below the tip is ONE
        aggregate signature + bitmap, and a late non-validator with
        fastsync OFF catches up through the consensus-path agg_commit
        lane (folded heights have no per-vote precommits to gossip)."""
        from tests.test_consensus_net import stop_net, wait_all_height
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node

        pvs = sorted(
            [bls_pv(b"net%d" % i) for i in range(4)], key=lambda pv: pv.address()
        )
        gen = GenesisDoc(
            chain_id="bls-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(
                    pv.address(), pv.get_pub_key(), 10, pop=pv.priv_key.pop()
                )
                for pv in pvs
            ],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        gen.validate_and_complete()  # PoP batch check must pass
        nodes = [
            _bls_node(
                _net_cfg(make_test_cfg, str(tmp_path / f"bls{i}")),
                gen, priv_validator=pv, db_backend="memdb",
            )
            for i, pv in enumerate(pvs)
        ]
        joiner = None
        try:
            for node in nodes:
                await node.start()
            for i in range(4):
                for j in range(i + 1, 4):
                    addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
                    await nodes[i].switch.dial_peer(addr)
            await wait_all_height(nodes, 3, timeout=120.0)
            for n in nodes:
                for h in range(1, 3):
                    commit = n.block_store.load_block_commit(h)
                    assert isinstance(commit, AggregateCommit), (
                        f"height {h} stored a per-vote commit — aggregation "
                        "did not engage on a uniform BLS set"
                    )
                    assert commit.signers.count() * 3 > 4 * 2
            hashes = {n.block_store.load_block(2).hash() for n in nodes}
            assert len(hashes) == 1

            # late joiner, fastsync disabled: consensus catchup is the
            # ONLY lane, and it must ship verified aggregate commits
            jcfg = _net_cfg(make_test_cfg, str(tmp_path / "joiner"))
            jcfg.base.fast_sync = False
            joiner = _bls_node(jcfg, gen, db_backend="memdb")
            await joiner.start()
            for n in nodes:
                addr = f"{n.node_key.id}@{n.switch.transport.listen_addr}"
                await joiner.switch.dial_peer(addr)
            target = min(n.block_store.height() for n in nodes)
            deadline = asyncio.get_event_loop().time() + 120.0
            while asyncio.get_event_loop().time() < deadline:
                if joiner.block_store.height() >= target:
                    break
                await asyncio.sleep(0.5)
            assert joiner.block_store.height() >= target, (
                "joiner never caught up over the agg_commit consensus lane"
            )
            assert isinstance(
                joiner.block_store.load_block_commit(2), AggregateCommit
            )
        finally:
            await stop_net(nodes + ([joiner] if joiner is not None else []))

    async def test_bls_node_restart_reconstructs_aggregate_last_commit(self, tmp_path):
        """A restarted BLS validator finds an aggregate SeenCommit — no
        per-vote signatures to rebuild a VoteSet from.  It must verify the
        single pairing, carry the AggregateLastCommit adapter, and keep
        committing (the next proposal embeds the aggregate verbatim)."""
        from tests.test_consensus_net import stop_net, wait_all_height
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node

        pv = bls_pv(b"solo")
        gen = GenesisDoc(
            chain_id="bls-solo",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pv.address(), pv.get_pub_key(), 10, pop=pv.priv_key.pop())
            ],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        home = str(tmp_path / "solo")
        cfg = _net_cfg(make_test_cfg, home)
        cfg.base.db_backend = "sqlite"  # the store must survive the restart
        node = _bls_node(cfg, gen, priv_validator=pv, db_backend="sqlite")
        try:
            await node.start()
            await wait_all_height([node], 2, timeout=60.0)
            stopped_at = node.block_store.height()
            assert isinstance(
                node.block_store.load_seen_commit(stopped_at), AggregateCommit
            )
        finally:
            await stop_net([node])

        cfg2 = _net_cfg(make_test_cfg, home)
        cfg2.base.db_backend = "sqlite"
        node2 = _bls_node(cfg2, gen, priv_validator=pv, db_backend="sqlite")
        try:
            await node2.start()
            assert isinstance(node2.consensus.rs.last_commit, AggregateLastCommit)
            await wait_all_height([node2], stopped_at + 1, timeout=60.0)
            assert isinstance(
                node2.block_store.load_block_commit(stopped_at), AggregateCommit
            )
        finally:
            await stop_net([node2])

    async def test_mixed_set_net_commits_without_aggregation(self, tmp_path):
        """2 ed25519 + 2 BLS validators in ONE set: consensus still
        commits via per-scheme verify routing, and every stored commit is
        a classic per-vote Commit (aggregation disabled itself)."""
        from tests.test_consensus_net import stop_net, wait_all_height
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.crypto.keys import Ed25519PrivKey
        from tendermint_tpu.node import Node

        pvs = sorted(
            [bls_pv(b"mix%d" % i) for i in range(2)]
            + [
                MockPV(priv_key=Ed25519PrivKey.from_secret(b"mix-ed%d" % i))
                for i in range(2)
            ],
            key=lambda pv: pv.address(),
        )
        gen = GenesisDoc(
            chain_id="mixed-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(
                    pv.address(), pv.get_pub_key(), 10,
                    pop=pv.priv_key.pop() if isinstance(pv.priv_key, BlsPrivKey) else b"",
                )
                for pv in pvs
            ],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        gen.validate_and_complete()
        nodes = [
            _bls_node(
                _net_cfg(make_test_cfg, str(tmp_path / f"mix{i}")),
                gen, priv_validator=pv, db_backend="memdb",
            )
            for i, pv in enumerate(pvs)
        ]
        try:
            for node in nodes:
                await node.start()
            for i in range(4):
                for j in range(i + 1, 4):
                    addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
                    await nodes[i].switch.dial_peer(addr)
            await wait_all_height(nodes, 3, timeout=120.0)
            for n in nodes:
                commit = n.block_store.load_block_commit(2)
                assert isinstance(commit, Commit) and not isinstance(
                    commit, AggregateCommit
                ), "mixed set must keep per-vote commits"
            hashes = {n.block_store.load_block(2).hash() for n in nodes}
            assert len(hashes) == 1
        finally:
            await stop_net(nodes)


class TestBlsNetStopBudget:
    @pytest.mark.skipif(
        not __import__(
            "tendermint_tpu.crypto.bls.ctier", fromlist=["available"]
        ).available(),
        reason="pure tier legitimately needs the raised stop budget",
    )
    async def test_bls_net_orderly_stop_within_default_budget(self, tmp_path):
        """With the C tier active, pairings drop the GIL and run in ~3 ms,
        so the held-GIL executor stalls that forced PR 9's STOP_TIMEOUT
        10→30 s raise are gone: an orderly BLS-net node stop must complete
        inside the DEFAULT budget (the autouse fixture above no longer
        raises it when this tier is active)."""
        import time

        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.libs.service import Service
        from tests.test_consensus_net import wait_all_height

        assert Service.STOP_TIMEOUT == 10.0, (
            "stop-budget fixture raised the timeout despite the C tier"
        )
        pvs = sorted(
            [bls_pv(b"stop%d" % i) for i in range(2)], key=lambda pv: pv.address()
        )
        gen = GenesisDoc(
            chain_id="bls-stop",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(
                    pv.address(), pv.get_pub_key(), 10, pop=pv.priv_key.pop()
                )
                for pv in pvs
            ],
            consensus_params=_FAST_IOTA_PARAMS,
        )
        gen.validate_and_complete()
        nodes = [
            _bls_node(
                _net_cfg(make_test_cfg, str(tmp_path / f"stop{i}")),
                gen, priv_validator=pv, db_backend="memdb",
            )
            for i, pv in enumerate(pvs)
        ]
        try:
            for node in nodes:
                await node.start()
            addr = f"{nodes[1].node_key.id}@{nodes[1].switch.transport.listen_addr}"
            await nodes[0].switch.dial_peer(addr)
            await wait_all_height(nodes, 2, timeout=60.0)
        finally:
            slow = []
            for node in nodes:
                if not node.is_running:
                    continue
                t0 = time.monotonic()
                await node.stop()
                elapsed = time.monotonic() - t0
                if elapsed >= Service.STOP_TIMEOUT:
                    slow.append(elapsed)
            assert not slow, (
                f"orderly BLS-net stop overran the default budget: {slow}"
            )


# ---------------------------------------------------------------------------
# C pairing tier (csrc/bls12_381.c): KATs + C-vs-pure differential
# ---------------------------------------------------------------------------


def _ctier_available() -> bool:
    from tendermint_tpu.crypto.bls import ctier

    return ctier.available()


@pytest.fixture
def force_pure_tier():
    """Route scheme/pairing through the pure reference tier for the
    duration of a test (the differential oracle side)."""
    from tendermint_tpu.crypto.bls import ctier

    ctier.set_forced("pure")
    yield
    ctier.set_forced(None)


def _non_subgroup_g1() -> bytes:
    """Compressed encoding of an E(Fp) point OUTSIDE the r-subgroup (the
    cofactor is ~2^125, so the first on-curve x that fails the subgroup
    check is one; searched deterministically)."""
    from tendermint_tpu.crypto.bls.fields import P, fp_sqrt

    x = 5
    while True:
        y = fp_sqrt((x * x * x + 4) % P)
        if y is not None:
            pt = (x, y, 1)
            if not curve.g1_in_subgroup(pt):
                return curve.g1_compress(pt)
        x += 1


def _non_subgroup_g2() -> bytes:
    from tendermint_tpu.crypto.bls.fields import f2_add, f2_mul, f2_sq, f2_sqrt

    x = (1, 0)
    while True:
        y = f2_sqrt(f2_add(f2_mul(f2_sq(x), x), (4, 4)))
        if y is not None:
            pt = (x, y, (1, 0))
            if not curve.g2_in_subgroup(pt):
                return curve.g2_compress(pt)
        x = (x[0] + 1, x[1])


@pytest.mark.skipif(not _ctier_available(), reason="no C toolchain")
class TestCTier:
    """The compiled tier must be VERDICT-IDENTICAL to the pure tower on
    every input — valid, invalid, and adversarial — and GT-output
    bit-identical where a value (not just a bool) crosses the boundary."""

    def test_generator_kats_replayed_through_c_tier(self):
        """The standard compressed generator encodings decode through the
        C tier to exactly the published points (and infinity encodings to
        the identity) — same KATs TestReferenceTier pins on the pure side."""
        from tendermint_tpu.crypto.bls import ctier

        b = ctier.g1_decompress(curve.g1_compress(curve.G1_GEN))
        assert b not in (None, ctier.INF)
        assert curve.g1_eq(ctier.g1_point(b), curve.G1_GEN)
        b2 = ctier.g2_decompress(curve.g2_compress(curve.G2_GEN))
        assert curve.g2_eq(ctier.g2_point(b2), curve.G2_GEN)
        assert ctier.g1_decompress(bytes([0xC0]) + b"\x00" * 47) is ctier.INF
        assert ctier.g2_decompress(bytes([0xC0]) + b"\x00" * 95) is ctier.INF

    def test_pairing_product_bit_identical_to_pure(self):
        """Same HHT final exponentiation ⇒ the full GT element matches the
        pure tier exactly, not just the ==1 verdict."""
        from tendermint_tpu.crypto.bls import ctier, pairing

        pairs = [
            (curve.G1_GEN, curve.G2_GEN),
            (curve.g1_mul(curve.G1_GEN, 7), curve.g2_mul(curve.G2_GEN, 11)),
        ]
        assert ctier.pairing_product_points(pairs) == pairing.pairing_product_pure(
            pairs
        )
        # identity operands are skipped identically
        with_inf = pairs + [(curve.G1_INF, curve.G2_GEN)]
        assert ctier.pairing_product_points(with_inf) == pairing.pairing_product_pure(
            with_inf
        )

    def test_scalar_mul_and_sums_differential(self):
        import random

        from tendermint_tpu.crypto.bls import ctier
        from tendermint_tpu.crypto.bls.fields import R

        rng = random.Random(9380)
        g1pts, g2pts = [], []
        for _ in range(8):
            k = rng.randrange(1, R)
            p1 = curve.g1_mul(curve.G1_GEN, k)
            p2 = curve.g2_mul(curve.G2_GEN, k)
            g1pts.append(p1)
            g2pts.append(p2)
            for sc in (1, 2, rng.randrange(1, R), R - 1):
                assert curve.g1_eq(
                    ctier.g1_point(ctier.g1_mul(ctier.g1_blob(p1), sc)),
                    curve.g1_mul(p1, sc),
                )
                assert curve.g2_eq(
                    ctier.g2_point(ctier.g2_mul(ctier.g2_blob(p2), sc)),
                    curve.g2_mul(p2, sc),
                )
        acc1 = curve.G1_INF
        for p in g1pts:
            acc1 = curve.g1_add(acc1, p)
        assert curve.g1_eq(
            ctier.g1_point(ctier.g1_sum([ctier.g1_blob(p) for p in g1pts])), acc1
        )
        acc2 = curve.G2_INF
        for p in g2pts:
            acc2 = curve.g2_add(acc2, p)
        assert curve.g2_eq(
            ctier.g2_point(ctier.g2_sum([ctier.g2_blob(p) for p in g2pts])), acc2
        )
        # P + (-P) folds to the identity, reported as INF not garbage
        neg = curve.g1_neg(g1pts[0])
        assert (
            ctier.g1_sum([ctier.g1_blob(g1pts[0]), ctier.g1_blob(neg)]) is ctier.INF
        )

    def test_sign_verify_identical_across_tiers(self, force_pure_tier):
        """Signatures are deterministic ([sk]H(m)) so the two tiers must
        produce BYTE-IDENTICAL signatures and identical verdicts; the C
        tier runs the whole hash-to-curve in C (bit-identical to the pure
        map, pinned by TestCTierHashToCurve below)."""
        from tendermint_tpu.crypto.bls import ctier

        sk = scheme.keygen(b"\x42" * 32)
        msgs = [b"", b"block at height 7", b"x" * 300]
        pure = {}
        assert scheme.active_tier() == "pure"
        pk_pure = scheme.sk_to_pk(sk)
        for m in msgs:
            sig = scheme.sign(sk, m)
            assert scheme.verify(pk_pure, m, sig)
            pure[m] = sig
        ctier.set_forced(None)
        assert scheme.active_tier() == "c"
        assert scheme.sk_to_pk(sk) == pk_pure
        for m in msgs:
            assert scheme.sign(sk, m) == pure[m]
            assert scheme.verify(pk_pure, m, pure[m])
            assert not scheme.verify(pk_pure, m + b"!", pure[m])
        pop = scheme.pop_prove(sk)
        assert scheme.pop_verify(pk_pure, pop)
        ctier.set_forced("pure")
        assert scheme.pop_prove(sk) == pop and scheme.pop_verify(pk_pure, pop)

    def test_differential_fuzz_aggregates(self, force_pure_tier):
        """Random keys/messages/aggregates through BOTH tiers: verdicts
        identical on the happy path, tampered signatures, wrong messages,
        swapped keys, and batch-with-liar attribution."""
        import random

        from tendermint_tpu.crypto.bls import ctier

        rng = random.Random(2302)
        sks = [scheme.keygen(bytes([i]) * 32) for i in range(1, 7)]
        pks = [scheme.sk_to_pk(sk) for sk in sks]
        msg = b"fuzz block"
        agg = scheme.aggregate_signatures([scheme.sign(sk, msg) for sk in sks])
        bad = bytearray(agg)
        bad[rng.randrange(len(bad))] ^= 0x40
        cases = []

        def snapshot(tag):
            cases.append((
                tag,
                scheme.fast_aggregate_verify(pks, msg, agg),
                scheme.fast_aggregate_verify(pks, msg, bytes(bad)),
                scheme.fast_aggregate_verify(pks, b"other", agg),
                scheme.fast_aggregate_verify(pks[:-1], msg, agg),
                scheme.aggregate_verify(
                    pks[:3],
                    [b"m1", b"m2", b"m3"],
                    scheme.aggregate_signatures(
                        [scheme.sign(sk, m) for sk, m in zip(sks, [b"m1", b"m2", b"m3"])]
                    ),
                ),
                scheme.batch_verify_aggregates(
                    [
                        (pks, msg, agg),
                        (pks, msg, bytes(bad)),
                        (pks[:2], msg, agg),
                    ]
                ),
            ))

        assert scheme.active_tier() == "pure"
        snapshot("pure")
        ctier.set_forced(None)
        assert scheme.active_tier() == "c"
        snapshot("c")
        assert cases[0][1:] == cases[1][1:], f"tier verdicts diverged: {cases}"
        assert cases[0][1] is True and cases[0][2] is False
        assert cases[0][6] == [True, False, False]

    def test_adversarial_encodings_identical_verdicts(self, force_pure_tier):
        """The adversarial lane: infinity aggregate pubkey (the PR 9
        regression), non-subgroup points, and mangled compressed encodings
        must be rejected IDENTICALLY by both tiers in both the strict and
        batch lanes."""
        from tendermint_tpu.crypto.bls import ctier
        from tendermint_tpu.crypto.bls.fields import R

        sk1 = scheme.keygen(b"\x07" * 32)
        sk2 = R - sk1  # pk1 + pk2 = INF: e(INF, H(m)) == 1 for ANY message
        inf_pair = [scheme.sk_to_pk(sk1), scheme.sk_to_pk(sk2)]
        forged = scheme.aggregate_signatures(
            [scheme.sign(sk1, b"any"), scheme.sign(sk2, b"any")]
        )
        pk = scheme.sk_to_pk(sk1)
        sig = scheme.sign(sk1, b"msg")
        mangled_pks = {
            "non_subgroup_g1": _non_subgroup_g1(),
            "compress_bit_clear": bytes([pk[0] & 0x7F]) + pk[1:],
            "x_ge_p": bytes([0x9F]) + b"\xff" * 47,
            "inf_with_tail": bytes([0xC0]) + b"\x00" * 46 + b"\x01",
            "inf_with_sign": bytes([0xE0]) + b"\x00" * 47,
            "flipped_bit": bytes([pk[0]]) + bytes([pk[1] ^ 1]) + pk[2:],
            "truncated": pk[:-1],
            "infinity_pk": bytes([0xC0]) + b"\x00" * 47,
        }
        mangled_sigs = {
            "non_subgroup_g2": _non_subgroup_g2(),
            "compress_bit_clear": bytes([sig[0] & 0x7F]) + sig[1:],
            "inf_with_tail": bytes([0xC0]) + b"\x00" * 94 + b"\x01",
            "truncated": sig[:-1],
        }

        def snapshot():
            verdicts = {}
            for tag, mpk in mangled_pks.items():
                verdicts[("verify", tag)] = scheme.verify(mpk, b"msg", sig)
                verdicts[("fagg", tag)] = scheme.fast_aggregate_verify(
                    [mpk], b"msg", sig
                )
                verdicts[("batch", tag)] = scheme.batch_verify_aggregates(
                    [([mpk], b"msg", sig)]
                )
            for tag, msig in mangled_sigs.items():
                verdicts[("sig", tag)] = scheme.verify(pk, b"msg", msig)
            verdicts["inf_apk_strict"] = scheme.fast_aggregate_verify(
                inf_pair, b"any", forged
            )
            verdicts["inf_apk_batch"] = scheme.batch_verify_aggregates(
                [(inf_pair, b"any", forged)]
            )
            return verdicts

        assert scheme.active_tier() == "pure"
        v_pure = snapshot()
        ctier.set_forced(None)
        assert scheme.active_tier() == "c"
        v_c = snapshot()
        assert v_pure == v_c, (
            "tier verdicts diverged: "
            + str({k: (v_pure[k], v_c[k]) for k in v_pure if v_pure[k] != v_c[k]})
        )
        # every adversarial input is REJECTED, not merely tier-consistent
        for k, v in v_c.items():
            if isinstance(v, list):
                assert v == [False], f"{k} accepted: {v}"
            else:
                assert v is False, f"{k} accepted"
        # curve-level decompress agrees with the C decoder on every case
        from tendermint_tpu.crypto.bls import ctier as ct

        for tag, mpk in mangled_pks.items():
            pure_pt = curve.g1_decompress(mpk) if len(mpk) == 48 else None
            c_blob = ct.g1_decompress(mpk)
            if tag == "infinity_pk":
                assert pure_pt == curve.G1_INF and c_blob is ct.INF
            else:
                assert pure_pt is None and c_blob is None, tag

    def test_memo_is_tier_aware(self, force_pure_tier):
        """A verdict cached by the pure tier must NOT be re-attributed to
        the C tier (telemetry honesty), including the restart-with-warm-
        memo path where the memo outlives a tier flip."""
        from tendermint_tpu.crypto.bls import ctier

        sk = scheme.keygen(b"\x99" * 32)
        pks = [scheme.sk_to_pk(sk)]
        msg, sig = b"memo", scheme.sign(sk, b"memo")
        assert scheme.active_tier() == "pure"
        scheme.memo_put(pks, msg, sig, True)
        assert scheme.memo_get(pks, msg, sig) is True
        ctier.set_forced(None)  # the "restart onto the fast tier" flip
        assert scheme.active_tier() == "c"
        assert scheme.memo_get(pks, msg, sig) is None, (
            "pure-tier verdict served under the C tier"
        )
        # warm the memo on the new tier: the hit comes back, and flipping
        # back to pure still finds ITS original entry
        scheme.memo_put(pks, msg, sig, True)
        assert scheme.memo_get(pks, msg, sig) is True
        ctier.set_forced("pure")
        assert scheme.memo_get(pks, msg, sig) is True


@pytest.mark.skipif(not _ctier_available(), reason="no C toolchain")
class TestCTierHashToCurve:
    """The C hash-to-curve lane (expand_message_xmd + SVDW map-to-G2 +
    clear cofactor, all in csrc/bls12_381.c): RFC 9380 K.1 KATs replayed
    through the C path, and C-vs-pure BIT-IDENTICAL affine points — the
    derived SvdW constants, fp2 sqrt root choice, and sgn0 fixes must all
    agree with the reference tier, not just land in the same orbit."""

    def test_expand_message_xmd_rfc9380_vectors_through_c(self):
        """Same §K.1 (SHA-256, len 0x20) vectors TestReferenceTier pins on
        the pure side, through bls381_expand_xmd."""
        from tendermint_tpu.crypto.bls import ctier

        dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
        vectors = [
            (b"", "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
            (b"abc", "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
            (b"abcdef0123456789",
             "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1"),
            (b"q128_" + b"q" * 128,
             "b23a1d2b4d97b2ef7785562a7e8bac7eed54ed6e97e29aa51bfe3f12ddad1ff9"),
            (b"a512_" + b"a" * 512,
             "4623227bcc01293b8c130bf771da8c298dede7383243dc0993d2d94823958c4c"),
        ]
        for msg, want in vectors:
            assert ctier.expand_message_xmd(msg, dst, 0x20).hex() == want

    def test_expand_message_xmd_differential(self):
        """Byte-identical to the pure expander across output lengths,
        multi-block ell, and the oversize-DST (>255 B) hashing rule."""
        from tendermint_tpu.crypto.bls import ctier

        dsts = [b"QUUX-V01-CS02-with-expander-SHA256-128", scheme.DST_SIG,
                b"D" * 300]
        for dst in dsts:
            for msg in (b"", b"abc", b"m" * 257):
                for n in (0, 1, 0x20, 0x21, 0x80, 255):
                    assert ctier.expand_message_xmd(msg, dst, n) == (
                        expand_message_xmd(msg, dst, n)
                    ), (dst[:8], msg[:8], n)
        with pytest.raises(ValueError):
            ctier.expand_message_xmd(b"x", scheme.DST_SIG, 256 * 32 + 1)

    def test_hash_to_g2_bit_identical_to_pure(self):
        """The acceptance pin: C and pure hash_to_g2 produce the SAME
        affine point bit for bit, over both suite DSTs and messages that
        exercise every map branch (e1/e2/x3 selection, sign flips)."""
        import hashlib as _hl

        from tendermint_tpu.crypto.bls import ctier

        msgs = [b"", b"consensus msg", b"x" * 300] + [
            _hl.sha256(bytes([i])).digest() for i in range(8)
        ]
        for dst in (scheme.DST_SIG, scheme.DST_POP):
            for msg in msgs:
                c_blob = ctier.hash_to_g2_blob(msg, dst)
                pure_blob = ctier.g2_blob(hash_to_g2(msg, dst))
                assert c_blob == pure_blob, (dst[:12], msg[:12])
                # and the point is in the right subgroup
                assert curve.g2_in_subgroup(ctier.g2_point(c_blob))

    def test_scheme_hash_cache_routes_through_c(self, force_pure_tier):
        """hash_to_g2_cached returns the same point whichever tier fills
        the memo — a warm pure cache stays valid across a tier flip."""
        from tendermint_tpu.crypto.bls import ctier

        msg = b"tier-flip hash cache"
        assert scheme.active_tier() == "pure"
        pure_pt = scheme.hash_to_g2_cached(msg, scheme.DST_SIG)
        ctier.set_forced(None)
        assert scheme.active_tier() == "c"
        # evict the warm entry so the C lane actually computes
        scheme._h2g.pop((msg, scheme.DST_SIG), None)
        c_pt = scheme.hash_to_g2_cached(msg, scheme.DST_SIG)
        assert curve.g2_eq(pure_pt, c_pt)
        assert ctier.g2_blob(pure_pt) == ctier.g2_blob(c_pt)


class TestCTierFallback:
    def test_no_toolchain_falls_back_pure_with_one_warning(self, monkeypatch, caplog):
        """A host without a working toolchain must land on the pure tier
        with ONE warning and a fully working scheme (the suite passing on
        such hosts is an acceptance criterion)."""
        import importlib
        import logging as _logging

        from tendermint_tpu.crypto.bls import ctier

        monkeypatch.setattr(ctier, "_lib", None)
        monkeypatch.setattr(ctier, "_lib_tried", False)
        monkeypatch.setattr(ctier, "_csrc_path", lambda: "/nonexistent-csrc")
        with caplog.at_level(_logging.WARNING, logger="tendermint_tpu.crypto.bls.ctier"):
            assert not ctier.available()
            assert not ctier.available()  # second probe: no second compile attempt
        warnings = [r for r in caplog.records if "C pairing tier" in r.message]
        assert len(warnings) == 1, caplog.records
        assert scheme.active_tier() == "pure"
        sk = scheme.keygen(b"\x55" * 32)
        pk = scheme.sk_to_pk(sk)
        sig = scheme.sign(sk, b"fallback")
        assert scheme.verify(pk, b"fallback", sig)
        assert not scheme.verify(pk, b"tampered", sig)
