"""Light-client tests (reference: lite2/verifier_test.go, client_test.go).

Chain fixtures are built header-by-header with real commits signed by
MockPVs, including validator-set rotation at a known height so bisection
is forced to descend (the lite2/client_test.go valset-change scenarios).
"""

import asyncio
import json

import pytest

from tendermint_tpu.lite2 import (
    BISECTION,
    Client,
    DivergedHeaderError,
    HTTPProvider,
    InvalidHeaderError,
    LocalProvider,
    MemStore,
    MockProvider,
    SEQUENCE,
    TrustOptions,
    verify_adjacent,
    verify_non_adjacent,
)
from tendermint_tpu.lite2.provider import ProviderError
from tendermint_tpu.lite2.store import DBStore
from tendermint_tpu.lite2.verifier import ErrNewValSetCantBeTrusted
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    Header,
    MockPV,
    PartSetHeader,
    SignedHeader,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE

CHAIN = "lite2-chain"
SEC = 1_000_000_000
T0 = 1_700_000_000_000_000_000
PERIOD = 3600 * SEC


def rand_vset(n, power=10):
    pvs = [MockPV() for _ in range(n)]
    vset = ValidatorSet([Validator.new(pv.get_pub_key(), power) for pv in pvs])
    pvs.sort(key=lambda pv: pv.address())
    return vset, pvs


def _commit(vset, pvs, height, block_id):
    vs = VoteSet(CHAIN, height, 0, PRECOMMIT_TYPE, vset)
    for pv in pvs:
        idx, _ = vset.get_by_address(pv.address())
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=height,
            round=0,
            block_id=block_id,
            timestamp_ns=T0 + height * SEC,
            validator_address=pv.address(),
            validator_index=idx,
        )
        pv.sign_vote(CHAIN, v)
        vs.add_vote(v)
    return vs.make_commit()


def make_chain(n_heights, valsets, t0=T0):
    """valsets: {height: (vset, pvs)} — lookup uses the greatest key <= h.
    Returns (headers {h: SignedHeader}, vals {h: ValidatorSet})."""

    def at(h):
        key = max(k for k in valsets if k <= h)
        return valsets[key]

    headers, vals = {}, {}
    last_block_id = BlockID()
    for h in range(1, n_heights + 1):
        vset, pvs = at(h)
        next_vset, _ = at(h + 1)
        header = Header(
            chain_id=CHAIN,
            height=h,
            time_ns=t0 + h * SEC,
            last_block_id=last_block_id,
            validators_hash=vset.hash(),
            next_validators_hash=next_vset.hash(),
            proposer_address=vset.validators[0].address,
        )
        bid = BlockID(header.hash(), PartSetHeader(1, header.hash()))
        commit = _commit(vset, pvs, h, bid)
        headers[h] = SignedHeader(header, commit)
        vals[h] = vset
        last_block_id = bid
    return headers, vals


class TestVerifier:
    def test_adjacent_ok_and_bad_next_vals(self):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(3, {1: (vset, pvs)})
        now = T0 + 10 * SEC
        verify_adjacent(CHAIN, headers[1], headers[2], vals[2], PERIOD, now, SEC)
        other_vset, _ = rand_vset(4)
        with pytest.raises(InvalidHeaderError):
            verify_adjacent(CHAIN, headers[2], headers[3], other_vset, PERIOD, now, SEC)

    def test_non_adjacent_ok(self):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(10, {1: (vset, pvs)})
        now = T0 + 20 * SEC
        verify_non_adjacent(
            CHAIN, headers[1], vals[1], headers[9], vals[9], PERIOD, now, SEC
        )

    def test_non_adjacent_insufficient_trust_power(self):
        vset_a, pvs_a = rand_vset(4)
        vset_b, pvs_b = rand_vset(4)
        headers, vals = make_chain(10, {1: (vset_a, pvs_a), 5: (vset_b, pvs_b)})
        now = T0 + 20 * SEC
        with pytest.raises(ErrNewValSetCantBeTrusted):
            verify_non_adjacent(
                CHAIN, headers[1], vals[1], headers[9], vals[9], PERIOD, now, SEC
            )

    def test_expired_trusted_header(self):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(5, {1: (vset, pvs)})
        with pytest.raises(InvalidHeaderError):
            verify_non_adjacent(
                CHAIN, headers[1], vals[1], headers[4], vals[4],
                PERIOD, T0 + PERIOD + SEC, SEC,
            )


def mk_client(headers, vals, trust_h=1, witnesses=(), mode=BISECTION, store=None, **kw):
    provider = MockProvider(CHAIN, headers, vals)
    opts = TrustOptions(PERIOD, trust_h, headers[trust_h].header.hash())
    return Client(
        CHAIN, opts, provider,
        witnesses=list(witnesses), store=store or MemStore(), mode=mode,
        now_fn=lambda: T0 + (max(headers) + 5) * SEC, **kw,
    )


class TestClient:
    async def test_bisection_static_valset_jumps(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(20, {1: (vset, pvs)})
        c = mk_client(headers, vals)
        sh = await c.verify_header_at_height(20)
        assert sh.height == 20
        assert (await c.trusted_header()).height == 20

    async def test_bisection_with_valset_rotation(self, tmp_path):
        """Full validator turnover at height 11: the direct jump can't be
        trusted, bisection must descend to the adjacent transition."""
        vset_a, pvs_a = rand_vset(4)
        vset_b, pvs_b = rand_vset(4)
        headers, vals = make_chain(20, {1: (vset_a, pvs_a), 11: (vset_b, pvs_b)})
        c = mk_client(headers, vals)
        sh = await c.verify_header_at_height(20)
        assert sh.height == 20
        # the transition header got stored on the way
        assert c.store.signed_header(11) is not None

    async def test_sequence_mode(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(8, {1: (vset, pvs)})
        c = mk_client(headers, vals, mode=SEQUENCE)
        sh = await c.verify_header_at_height(8)
        assert sh.height == 8
        # every intermediate header verified & stored
        assert sorted(c.store.heights()) == list(range(1, 9))

    async def test_backwards(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(15, {1: (vset, pvs)})
        c = mk_client(headers, vals, trust_h=15)
        sh = await c.verify_header_at_height(5)
        assert sh.height == 5
        assert sh.header.hash() == headers[5].header.hash()

    async def test_update_to_latest(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(12, {1: (vset, pvs)})
        c = mk_client(headers, vals)
        sh = await c.update()
        assert sh.height == 12

    async def test_witness_divergence_detected(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(10, {1: (vset, pvs)})
        # witness serves a forked chain: same keys, different block times
        fork_headers, fork_vals = make_chain(10, {1: (vset, pvs)}, t0=T0 + SEC // 2)
        assert fork_headers[10].header.hash() != headers[10].header.hash()
        forked = MockProvider(CHAIN, fork_headers, fork_vals)
        c = mk_client(headers, vals, witnesses=[forked])
        with pytest.raises(DivergedHeaderError):
            await c.verify_header_at_height(10)
        # the lying primary's headers were rolled back, not left trusted:
        # only the trust-root height may remain in the store, and repeat
        # queries keep failing rather than serving the poisoned header
        assert c.store.signed_header(10) is None
        assert all(h == 1 for h in c.store.heights())
        with pytest.raises(DivergedHeaderError):
            await c.verify_header_at_height(10)

    async def test_replace_primary(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(6, {1: (vset, pvs)})
        good = MockProvider(CHAIN, headers, vals)
        c = mk_client(headers, vals, witnesses=[good])
        await c.replace_primary()
        assert c.primary is good
        sh = await c.verify_header_at_height(6)
        assert sh.height == 6

    async def test_init_rejects_wrong_hash(self, tmp_path):
        from tendermint_tpu.lite2.client import LightClientError

        vset, pvs = rand_vset(4)
        headers, vals = make_chain(4, {1: (vset, pvs)})
        provider = MockProvider(CHAIN, headers, vals)
        opts = TrustOptions(PERIOD, 1, b"\x13" * 32)
        c = Client(CHAIN, opts, provider, now_fn=lambda: T0 + 9 * SEC)
        with pytest.raises(LightClientError):
            await c.initialize()

    async def test_pruning(self, tmp_path):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(10, {1: (vset, pvs)})
        c = mk_client(headers, vals, mode=SEQUENCE, max_retained_headers=3)
        await c.verify_header_at_height(10)
        assert len(c.store.heights()) <= 3
        assert c.store.latest_height() == 10

    async def test_db_store_roundtrip(self, tmp_path):
        from tendermint_tpu.libs.kvstore import open_db

        vset, pvs = rand_vset(4)
        headers, vals = make_chain(5, {1: (vset, pvs)})
        store = DBStore(open_db("lite", str(tmp_path), "sqlite"))
        c = mk_client(headers, vals, store=store)
        await c.verify_header_at_height(5)
        sh = store.signed_header(5)
        assert sh is not None and sh.header.hash() == headers[5].header.hash()
        vs = store.validator_set(5)
        assert vs.hash() == vals[5].hash()


class TestAgainstLiveNode:
    async def test_light_sync_from_local_node(self, tmp_path):
        """lite2 against a real node through the RPC surface: trust block 1
        by hash, then verify the node's latest header (BASELINE config #4
        shape, small scale)."""
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node

        from tendermint_tpu.types.params import BlockParams, ConsensusParams

        pv = MockPV()
        gen = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=T0,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
            # iota=1ms: this node commits ~10 blocks/sec (skip_timeout_commit),
            # so the default 1000 ms BFT-time minimum step would race header
            # time ~0.9 s/block ahead of wall clock — under suite load the
            # light client then (correctly) rejects headers "from the future"
            # past max_clock_drift.  The chain must not outrun the wall clock.
            consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        )
        cfg = make_test_cfg(str(tmp_path / "lightnode"))
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        await node.start()
        try:
            async def reach(h):
                while node.block_store.height() < h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(reach(5), 30.0)
            primary = HTTPProvider(CHAIN, node.rpc_server.listen_addr)
            trusted = await primary.signed_header(2)
            c = Client(
                CHAIN,
                TrustOptions(PERIOD, 2, trusted.header.hash()),
                primary,
                witnesses=[LocalProvider(node)],
            )
            sh = await c.update()
            assert sh is not None and sh.height >= 5
            await primary.close()
        finally:
            await node.stop()

    async def test_fast_chain_headers_stay_within_clock_drift(self, tmp_path):
        """Regression for the live-sync flake: a chain committing many
        blocks per second must keep header time within lite2's
        max_clock_drift of wall clock (time_iota_ms=1 genesis), no matter
        how many blocks land before a light client syncs."""
        import time as _time

        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.lite2.client import _DEFAULT_MAX_CLOCK_DRIFT_NS
        from tendermint_tpu.node import Node
        from tendermint_tpu.types.params import BlockParams, ConsensusParams

        pv = MockPV()
        gen = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=_time.time_ns(),
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
            consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        )
        cfg = make_test_cfg(str(tmp_path / "fastnode"))
        cfg.base.db_backend = "memdb"
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        await node.start()
        try:
            async def reach(h):
                while node.block_store.height() < h:
                    await asyncio.sleep(0.02)

            # enough blocks that the old 1000 ms iota would have drifted
            # header time well past the 10 s max_clock_drift
            await asyncio.wait_for(reach(15), 60.0)
            meta = node.block_store.load_block_meta(node.block_store.height())
            drift_ns = meta.header.time_ns - _time.time_ns()
            assert drift_ns < _DEFAULT_MAX_CLOCK_DRIFT_NS, (
                f"header time drifted {drift_ns / 1e9:.2f}s into the future"
            )
            # and tightly: iota=1ms over ~15 blocks is at most tens of ms
            assert drift_ns < 1_000_000_000
        finally:
            await node.stop()


class TestClientHardening:
    """PR 19 satellites: parallel witness cross-check with per-witness
    timeout + demotion, per-pass bisection fetch memoization, and the
    concurrent diverged-rollback race (a loser's rollback must not delete
    a concurrent winner's insertions)."""

    async def test_hung_witness_does_not_stall_verification(self):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(8, {1: (vset, pvs)})

        class HungProvider(MockProvider):
            async def signed_header(self, height):
                await asyncio.Event().wait()  # never returns

        honest = MockProvider(CHAIN, headers, vals)
        hung = HungProvider(CHAIN, headers, vals)
        c = mk_client(
            headers, vals, witnesses=[hung, honest], witness_timeout_s=0.05
        )
        t0 = asyncio.get_event_loop().time()
        sh = await c.verify_header_at_height(8)
        assert sh.height == 8
        # bounded by the per-witness timeout, not by the hung socket
        assert asyncio.get_event_loop().time() - t0 < 2.0

    async def test_erroring_witness_demoted_and_kept_out_of_promotion(self):
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(8, {1: (vset, pvs)})

        class DeadProvider(MockProvider):
            async def signed_header(self, height):
                raise ProviderError("connection refused")

        dead = DeadProvider(CHAIN)
        honest = MockProvider(CHAIN, headers, vals)
        demoted = []
        c = mk_client(
            headers, vals, witnesses=[dead, honest],
            witness_error_threshold=2, on_witness_demoted=demoted.append,
        )
        await c.verify_header_at_height(3)
        await c.verify_header_at_height(5)
        assert demoted == [dead]
        assert c.witnesses == [honest]
        assert c.demoted_witnesses == [dead]
        # replace_primary promotes from the honest pool, never the dead one
        await c.replace_primary()
        assert c.primary is honest

    async def test_bisection_memoizes_per_pass_fetches(self):
        vset, pvs = rand_vset(4)
        # valset rotation at 11 forces bisection to descend and revisit
        # pivots instead of jumping root->target in one step
        vset2, pvs2 = rand_vset(4)
        headers, vals = make_chain(20, {1: (vset, pvs), 11: (vset2, pvs2)})

        class CountingProvider(MockProvider):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.fetches = {}

            async def signed_header(self, height):
                self.fetches[height] = self.fetches.get(height, 0) + 1
                return await super().signed_header(height)

        provider = CountingProvider(CHAIN, headers, vals)
        c = Client(
            CHAIN,
            TrustOptions(PERIOD, 1, headers[1].header.hash()),
            provider,
            store=MemStore(),
            now_fn=lambda: T0 + 25 * SEC,
        )
        sh = await c.verify_header_at_height(20)
        assert sh.height == 20
        # the pass-local memo bounds every height to ONE header fetch
        # (initialize() fetches the root once more than the pass itself)
        over = {h: n for h, n in provider.fetches.items() if n > 1 and h != 1}
        assert not over, f"re-fetched during one pass: {over}"

    async def test_concurrent_diverged_rollback_spares_winner(self):
        """The S4 race: pass B (lying-witness divergence at its target)
        rolls back while pass A concurrently verifies other heights.  A
        before-snapshot rollback would delete A's fresh insertions; the
        pass-local saved-set must not."""
        vset, pvs = rand_vset(4)
        headers, vals = make_chain(20, {1: (vset, pvs)})
        fork_headers, _ = make_chain(20, {1: (vset, pvs)}, t0=T0 + SEC // 2)

        gate = asyncio.Event()

        class GatedProvider(MockProvider):
            """Stalls B's witness query until A has persisted its span."""

            async def signed_header(self, height):
                await gate.wait()
                return await super().signed_header(height)

        lying = GatedProvider(CHAIN, fork_headers, vals)
        c = mk_client(headers, vals, mode=SEQUENCE, witnesses=[lying])

        async def pass_b():
            # sequence-verifies 1..12, then the witness compare diverges
            with pytest.raises(DivergedHeaderError):
                await c.verify_header_at_height(12)

        async def pass_a():
            # a second client view over the SAME store, honest witness
            honest = MockProvider(CHAIN, headers, vals)
            c2 = mk_client(headers, vals, witnesses=[honest], store=c.store,
                           mode=SEQUENCE)
            await c2.verify_header_at_height(16)
            gate.set()  # only now may B's witness answer (and diverge)

        task_b = asyncio.ensure_future(pass_b())
        await asyncio.sleep(0)  # let B persist 1..12 and block on the witness
        await pass_a()
        await task_b
        # B's rollback removed ONLY its own insertions (2..12 minus what A
        # re-persisted is gone is acceptable; what matters is A's span
        # 13..16 — inserted by the WINNER while B was in flight — survives)
        for h in (13, 14, 15, 16):
            assert c.store.signed_header(h) is not None, f"winner height {h} lost"
        assert c.store.signed_header(16).header.hash() == headers[16].header.hash()


class TestBoundedProxyBody:
    """PR 19 satellite S1: LightProxy._handle_post reads a BOUNDED body
    (PR 11 ingress discipline) and rejects oversized or malformed input
    with explicit JSON-RPC errors instead of buffering unboundedly."""

    def _proxy(self, max_body=256):
        from tendermint_tpu.lite2.proxy import LightProxy

        vset, pvs = rand_vset(4)
        headers, vals = make_chain(4, {1: (vset, pvs)})
        return LightProxy(mk_client(headers, vals), "tcp://127.0.0.1:0",
                          max_body_bytes=max_body)

    class _FakeContent:
        def __init__(self, body):
            self._body = body

        async def read(self, n):
            chunk, self._body = self._body[:n], self._body[n:]
            return chunk

    class _FakeRequest:
        def __init__(self, body):
            self.content = TestBoundedProxyBody._FakeContent(body)

    async def test_oversized_body_rejected_with_named_cap(self):
        proxy = self._proxy(max_body=64)
        resp = await proxy._handle_post(self._FakeRequest(b"x" * 200))
        out = json.loads(resp.body)
        assert out["error"]["code"] == -32600
        assert "64" in out["error"]["message"]

    async def test_malformed_json_and_shape(self):
        proxy = self._proxy()
        resp = await proxy._handle_post(self._FakeRequest(b"{nope"))
        assert json.loads(resp.body)["error"]["code"] == -32700
        resp = await proxy._handle_post(self._FakeRequest(b'[1,2,3]'))
        assert json.loads(resp.body)["error"]["code"] == -32600

    async def test_body_at_limit_accepted(self):
        proxy = self._proxy(max_body=4096)
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": "status",
                          "params": {}}).encode()
        resp = await proxy._handle_post(self._FakeRequest(req))
        out = json.loads(resp.body)
        assert "result" in out and out["result"]["light_client"] is True
