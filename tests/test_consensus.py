"""Consensus tests: WAL framing, ticker, single-validator end-to-end block
production, crash replay, handshake.

Coverage model: consensus/state_test.go (proposal/vote flow),
consensus/wal_test.go, consensus/replay_test.go (crash/restart),
the minimum end-to-end slice of SURVEY.md §7 stage 5.
"""

import asyncio

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.consensus.wal import (
    NilWAL,
    WAL,
    WALCorruptionError,
    decode_records,
    encode_record,
)
from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from tendermint_tpu.node import Node, only_validator_is_us
from tendermint_tpu.proxy import default_client_creator
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.events import EVENT_NEW_BLOCK, EventBus, query_for_event

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "cs-test-chain"


def make_genesis(pvs, power=10):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), power) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


def solo_node(tmp_path, backend="memdb", proxy_app="kvstore"):
    pv = MockPV()
    cfg = make_test_cfg(str(tmp_path))
    cfg.rpc.laddr = ""
    cfg.base.db_backend = backend
    cfg.base.proxy_app = proxy_app
    gen = make_genesis([pv])
    node = Node(cfg, gen, priv_validator=pv, db_backend=backend)
    return node, pv


async def wait_blocks(node, n, timeout=20.0):
    sub = await node.event_bus.subscribe("test", query_for_event(EVENT_NEW_BLOCK), buffer=100)
    heights = []
    async def consume():
        async for msg in sub:
            heights.append(msg.data.data["block"].height)
            if len(heights) >= n:
                return
    await asyncio.wait_for(consume(), timeout)
    return heights


class TestWAL:
    def test_record_roundtrip(self):
        recs = [
            {"type": "timeout", "height": 1, "round": 0, "step": 1, "duration": 0.1},
            {"type": "endheight", "height": 1},
            {"type": "roundstate", "height": 2, "round": 0, "step": "NewHeight"},
        ]
        raw = b"".join(encode_record(dict(r)) for r in recs)
        decoded = list(decode_records(raw))
        for want, got in zip(recs, decoded):
            for k, v in want.items():
                assert got[k] == v

    def test_torn_tail_tolerated(self):
        raw = encode_record({"type": "endheight", "height": 5})
        decoded = list(decode_records(raw + raw[: len(raw) // 2]))
        assert len(decoded) == 1

    def test_crc_corruption_detected(self):
        raw = bytearray(encode_record({"type": "endheight", "height": 5}))
        raw[10] ^= 0xFF
        with pytest.raises(WALCorruptionError):
            list(decode_records(bytes(raw)))

    def test_search_for_end_height(self, tmp_path):
        wal = WAL(str(tmp_path / "wal"))
        wal.write_sync({"type": "msg", "peer_id": "", "msg": {"type": "x"}})
        wal.write_end_height(1)
        wal.write_sync({"type": "msg", "peer_id": "", "msg": {"type": "y"}})
        wal.write_end_height(2)
        wal.write_sync({"type": "msg", "peer_id": "", "msg": {"type": "z"}})
        records, found = wal.search_for_end_height(2)
        assert found and len(records) == 1 and records[0]["msg"]["type"] == "z"
        records, found = wal.search_for_end_height(1)
        assert found and len(records) == 3
        records, found = wal.search_for_end_height(9)
        assert not found and records is None
        wal.close()


class TestTicker:
    async def test_fires_and_replaces(self):
        t = TimeoutTicker()
        await t.start()
        try:
            t.schedule_timeout(TimeoutInfo(5.0, 1, 0, 3))
            # a later step replaces the pending long timer
            t.schedule_timeout(TimeoutInfo(0.01, 1, 0, 4))
            ti = await asyncio.wait_for(t.chan().get(), 1.0)
            assert ti.step == 4
            # an EARLIER step must not replace a pending later one
            t.schedule_timeout(TimeoutInfo(0.01, 1, 0, 5))
            t.schedule_timeout(TimeoutInfo(0.001, 1, 0, 4))
            ti = await asyncio.wait_for(t.chan().get(), 1.0)
            assert ti.step == 5
        finally:
            await t.stop()


class TestSoloNode:
    async def test_produces_blocks_kvstore(self, tmp_path):
        node, pv = solo_node(tmp_path)
        await node.start()
        try:
            heights = await wait_blocks(node, 3)
            assert heights == [1, 2, 3]
            assert node.block_store.height() >= 3
            b1 = node.block_store.load_block(1)
            assert b1.header.proposer_address == pv.address()
            b2 = node.block_store.load_block(2)
            # chain links: block 2's last_block_id points at block 1
            assert b2.header.last_block_id.hash == b1.hash()
            commit1 = node.block_store.load_block_commit(1)
            assert commit1.height == 1
        finally:
            await node.stop()

    async def test_txs_commit_and_query(self, tmp_path):
        node, _ = solo_node(tmp_path)
        await node.start()
        try:
            await wait_blocks(node, 1)
            res = await node.mempool.check_tx(b"k1=v1")
            assert res.is_ok
            # wait for the tx to be committed
            for _ in range(100):
                await asyncio.sleep(0.05)
                if node.mempool.size() == 0 and node.block_store.height() > 1:
                    break
            from tendermint_tpu.abci.types import RequestQuery

            q = await node.proxy_app.query().query(RequestQuery(data=b"k1"))
            assert q.value == b"v1"
            # indexed by the tx indexer through the event bus
            await asyncio.sleep(0.1)
            from tendermint_tpu.types.tx import tx_hash

            indexed = node.tx_indexer.get(tx_hash(b"k1=v1"))
            assert indexed is not None and indexed["tx"] == b"k1=v1"
        finally:
            await node.stop()

    async def test_only_validator_is_us(self, tmp_path):
        node, pv = solo_node(tmp_path)
        assert only_validator_is_us(node.state, pv)
        assert not only_validator_is_us(node.state, MockPV())

    async def test_future_block_time_gets_nil_prevote(self, tmp_path):
        """Propose-side clock sanity (reference state/validation.go block
        time checks, extended to prevote time): a proposal whose header
        time is past local now + proposal_clock_drift must draw a nil
        prevote — a committed far-future block would be rejected by every
        light client — while a sane proposal commits normally."""
        import dataclasses
        import time

        from tendermint_tpu.types.block import Block

        node, pv = solo_node(tmp_path)
        await node.start()
        try:
            await wait_blocks(node, 1)
            cs = node.consensus
            drift_ns = int(cs.config.proposal_clock_drift * 1e9)
            assert drift_ns > 0  # guard enabled by default
            orig_create = cs._create_proposal_block

            def future_create():
                created = orig_create()
                if created is None:
                    return None
                block, _ = created
                from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES

                bad = Block(
                    dataclasses.replace(
                        block.header, time_ns=time.time_ns() + 2 * drift_ns
                    ),
                    block.txs,
                    block.evidence,
                    block.last_commit,
                )
                return bad, bad.make_part_set(BLOCK_PART_SIZE_BYTES)

            cs._create_proposal_block = future_create
            await asyncio.sleep(0.3)  # drain proposals created pre-patch
            stuck_h = node.block_store.height()
            await asyncio.sleep(1.0)
            # the solo validator nil-prevotes its own future-stamped blocks,
            # so nothing can commit while the clock lies
            assert node.block_store.height() == stuck_h
            cs._create_proposal_block = orig_create
            async def resumed():
                while node.block_store.height() <= stuck_h:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(resumed(), 20.0)
        finally:
            await node.stop()


class TestCrashRestart:
    async def test_restart_resumes_from_store(self, tmp_path):
        # run a node with durable storage, stop it, restart: handshake +
        # WAL replay must resume from the persisted height without re-signing
        # conflicts (consensus/replay_test.go spirit)
        from tendermint_tpu.libs.kvstore import SQLiteDB

        node, pv = solo_node(tmp_path, backend="sqlite")
        await node.start()
        try:
            await wait_blocks(node, 3)
        finally:
            await node.stop()
        h1 = node.block_store.height()
        assert h1 >= 3

        cfg = make_test_cfg(str(tmp_path))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "sqlite"
        gen = make_genesis([pv])
        node2 = Node(cfg, gen, priv_validator=pv, db_backend="sqlite")
        assert node2.block_store.height() == h1
        await node2.start()
        try:
            await wait_blocks(node2, 2)
            assert node2.block_store.height() > h1
            # the chain is continuous across the restart
            for h in range(2, node2.block_store.height() + 1):
                b = node2.block_store.load_block(h)
                prev = node2.block_store.load_block(h - 1)
                if b is None or prev is None:  # pruned is fine
                    continue
                assert b.header.last_block_id.hash == prev.hash()
        finally:
            await node2.stop()
