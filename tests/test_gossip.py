"""Event-driven batched gossip tests.

The reference gossips one vote / one block part per peer per
`peer_gossip_sleep_duration` tick (consensus/reactor.go:467/606).  This
layer replaces the pacing with per-peer wakeup events and byte-capped
`vote_batch` frames; these tests pin the three contracts that matter:

1. latency — a vote created on node A lands in node B's vote set well
   under the gossip sleep (the wakeup path, not the tick, carries it);
2. batch shape — a received vote_batch reaches the AsyncBatchVerifier as
   exactly ONE flush (one host-prep pass, the engine's batch shape);
3. wire compatibility — a batched node and a legacy single-vote node
   (knob forced off, NodeInfo advertises gossip_version 0) commit blocks
   together, with the fallback path demonstrably exercised.
"""

import asyncio
import time
from types import SimpleNamespace

import pytest

from tendermint_tpu.config import ConsensusConfig, test_config as make_test_cfg
from tendermint_tpu.consensus.reactor import (
    VOTE_CHANNEL,
    ConsensusReactor,
    PeerRoundState,
    _enc,
)
from tendermint_tpu.consensus.types import HeightVoteSet, RoundState
from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.encoding import codec
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.libs.metrics import ConsensusMetrics
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.node_info import GOSSIP_BATCH_VERSION, NodeInfo
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    PartSetHeader,
    Validator,
    ValidatorSet,
    Vote,
)
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "gossip-test-chain"


# ---------------------------------------------------------------------------
# unit-level fixtures
# ---------------------------------------------------------------------------


class _CountingVerifier(BatchVerifier):
    """Host-path verifier that counts device/host dispatches — one call ==
    one engine flush reached the verify kernel."""

    def __init__(self):
        super().__init__(min_device_batch=10**9)  # always the host path
        self.calls = []

    def start_warmup(self):
        # the host path never dispatches to the device: skip the background
        # bucket compile thread (pure core contention on the CI container)
        return self

    def verify(self, pubkeys, msgs, sigs):
        self.calls.append(len(sigs))
        return super().verify(pubkeys, msgs, sigs)


class _FakeSwitch:
    def __init__(self):
        self.stopped = []

    async def stop_peer_for_error(self, peer, reason):
        self.stopped.append((peer.id, reason))


class _FakeCS:
    """The slice of ConsensusState the reactor's vote-receive path uses."""

    def __init__(self, vset, height=5):
        self.config = ConsensusConfig()
        self.rs = RoundState(
            height=height,
            validators=vset,
            votes=HeightVoteSet(CHAIN_ID, height, vset),
            last_validators=None,
        )
        self.sm_state = SimpleNamespace(chain_id=CHAIN_ID)
        self.on_new_round_step = []
        self.on_vote = []
        self.on_valid_block = []
        self.on_proposal = []
        self.on_new_block_part = []
        self.metrics = ConsensusMetrics()
        self.recorder = tracing.NOP
        self.added = []

    async def add_vote_input(self, vote, peer_id="", verified=False):
        self.added.append((vote, peer_id, verified))


def _vset_and_votes(n=4, height=5, vote_type=PREVOTE_TYPE):
    pvs = [MockPV() for _ in range(n)]
    vset = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
    pvs.sort(key=lambda pv: pv.address())
    votes = []
    for pv in pvs:
        i, _ = vset.get_by_address(pv.address())
        v = Vote(
            type=vote_type, height=height, round=0, block_id=BlockID(),
            timestamp_ns=1, validator_address=pv.address(), validator_index=i,
        )
        pv.sign_vote(CHAIN_ID, v)
        votes.append(v)
    return vset, votes


def _batch_msg(votes):
    return _enc("vote_batch", {"votes": [v.wire() for v in votes]})


class TestVoteWire:
    def test_wire_encode_once_and_roundtrip(self):
        _, votes = _vset_and_votes(2)
        v = votes[0]
        w1 = v.wire()
        assert v.wire() is w1  # cached, not re-encoded
        back = codec.loads(w1)
        assert isinstance(back, Vote)
        assert back == v

    def test_node_info_defaults_legacy_for_old_peers(self):
        # a handshake dict from a node predating the field must resolve to
        # the conservative legacy capability, not the batched one
        old = NodeInfo.from_dict({"node_id": "ab" * 20})
        assert old.gossip_version == 0
        new = NodeInfo.from_dict({"node_id": "ab" * 20, "gossip_version": 1})
        assert new.gossip_version == GOSSIP_BATCH_VERSION


class TestVerifyMany:
    async def test_single_flush_for_whole_batch(self):
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            keys = [Ed25519PrivKey.from_secret(b"vm%d" % i) for i in range(50)]
            msgs = [b"payload-%d" % i for i in range(50)]
            items = [
                (k.pub_key().bytes(), m, k.sign(m)) for k, m in zip(keys, msgs)
            ]
            items[7] = (items[7][0], items[7][1], bytes(64))  # one bad sig
            results = await asyncio.gather(*svc.verify_many(items))
            assert len(cv.calls) == 1 and cv.calls[0] == 50
            assert results[7] is False
            assert all(r for i, r in enumerate(results) if i != 7)
        finally:
            await svc.stop()


class TestVoteBatchReceive:
    async def test_batch_is_one_engine_flush_and_lands_verified(self):
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            reactor = ConsensusReactor(cs, async_verifier=svc)
            reactor.switch = _FakeSwitch()
            peer = SimpleNamespace(id="batch-peer-0000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            await reactor.receive(VOTE_CHANNEL, peer, _batch_msg(votes))
            assert len(cv.calls) == 1 and cv.calls[0] == len(votes), (
                "a vote_batch must reach the engine as exactly one flush"
            )
            assert len(cs.added) == len(votes)
            assert all(verified for _, _, verified in cs.added)
            assert reactor.switch.stopped == []
        finally:
            await svc.stop()

    async def test_bad_signature_in_batch_stops_peer(self):
        vset, votes = _vset_and_votes(4)
        votes[2].signature = bytes(64)
        cs = _FakeCS(vset)
        svc = AsyncBatchVerifier(_CountingVerifier())
        await svc.start()
        try:
            reactor = ConsensusReactor(cs, async_verifier=svc)
            reactor.switch = _FakeSwitch()
            peer = SimpleNamespace(id="badsig-peer-000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            await reactor.receive(VOTE_CHANNEL, peer, _batch_msg(votes))
            assert reactor.switch.stopped, "invalid batch signature must stop the peer"
            assert cs.added == []
        finally:
            await svc.stop()

    async def test_oversized_batch_stops_peer(self):
        vset, votes = _vset_and_votes(1)
        cs = _FakeCS(vset)
        reactor = ConsensusReactor(cs, async_verifier=None)
        reactor.switch = _FakeSwitch()
        peer = SimpleNamespace(id="flood-peer-0000", gossip_version=1)
        reactor.peer_states[peer.id] = PeerRoundState()
        msg = _enc("vote_batch", {"votes": [votes[0].wire()] * 16385})
        await reactor.receive(VOTE_CHANNEL, peer, msg)
        assert reactor.switch.stopped


class TestRarestFirst:
    def _reactor(self, vset):
        return ConsensusReactor(_FakeCS(vset))

    def test_pick_parts_prefers_parts_fewest_peers_hold(self):
        vset, _ = _vset_and_votes(2)
        reactor = self._reactor(vset)
        header = PartSetHeader(4, b"\x01" * 32)
        ps = PeerRoundState()
        ps.proposal_block_parts_header = header
        ps.proposal_block_parts = BitArray(4)
        other = PeerRoundState()
        other.proposal_block_parts_header = header
        other.proposal_block_parts = BitArray.from_indices(4, [0, 1])
        reactor.peer_states = {"a": ps, "b": other}
        missing = BitArray.from_indices(4, range(4))
        got = reactor._pick_parts(missing, ps, 2)
        # parts 2 and 3 are held by no other peer: they go first
        assert set(got) == {2, 3}
        assert reactor._pick_parts(missing, ps, 10) != []  # window respected
        assert len(reactor._pick_parts(missing, ps, 3)) == 3

    def test_pick_parts_ignores_mismatched_headers(self):
        vset, _ = _vset_and_votes(2)
        reactor = self._reactor(vset)
        ps = PeerRoundState()
        ps.proposal_block_parts_header = PartSetHeader(2, b"\x01" * 32)
        other = PeerRoundState()
        other.proposal_block_parts_header = PartSetHeader(2, b"\x02" * 32)
        other.proposal_block_parts = BitArray.from_indices(2, [0])
        reactor.peer_states = {"a": ps, "b": other}
        missing = BitArray.from_indices(2, range(2))
        assert len(reactor._pick_parts(missing, ps, 2)) == 2


class TestMaj23Dedupe:
    async def test_identical_claim_sent_once_then_expires(self):
        vset, _ = _vset_and_votes(2)
        cs = _FakeCS(vset)
        reactor = ConsensusReactor(cs)
        sent = []

        class _Peer:
            id = "maj23-peer-0000"

            async def send(self, chan, msg):
                sent.append((chan, msg))
                return True

        peer, ps = _Peer(), PeerRoundState()
        bid = BlockID(b"\x05" * 32, PartSetHeader(1, b"\x06" * 32))
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        assert len(sent) == 1, "identical maj23 claim must not be re-sent"
        # a different blockID is a different claim
        bid2 = BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32))
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid2)
        assert len(sent) == 2
        # entries expire so the VoteSetBits repair can re-fire
        key = (5, 0, PREVOTE_TYPE, bid.key())
        ps.maj23_sent[key] -= 10 * cs.config.peer_query_maj23_sleep_duration + 1
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        assert len(sent) == 3
        # peer height change clears the table
        ps.apply_new_round_step({"height": 6, "round": 0, "step": 1})
        assert ps.maj23_sent == {}


# ---------------------------------------------------------------------------
# live-net tests
# ---------------------------------------------------------------------------


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


async def _make_net(tmp_path, n, name="g", mutate_cfg=None):
    pvs = sorted([MockPV() for _ in range(n)], key=lambda pv: pv.address())
    gen = _gen(pvs)
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = make_test_cfg(str(tmp_path / f"{name}{i}"))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "memdb"
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.1
        if mutate_cfg is not None:
            mutate_cfg(i, cfg)
        nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
    for node in nodes:
        await node.start()
    for i in range(n):
        for j in range(i + 1, n):
            addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
            await nodes[i].switch.dial_peer(addr)
    return nodes, pvs


async def _stop_net(nodes):
    for node in nodes:
        if node.is_running:
            await node.stop()


async def _wait_all_height(nodes, h, timeout=45.0):
    async def _wait():
        while not all(n.block_store.height() >= h for n in nodes):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(_wait(), timeout)


class TestEventDrivenLatency:
    async def test_vote_lands_well_under_gossip_sleep(self, tmp_path):
        """Regression for the tentpole claim: with the polling tick cranked
        to 1.5 s, a vote signed on node A must land in node B's vote set in
        a small fraction of that — only the event wakeups can carry it."""
        SLEEP = 1.5

        def slow_tick(i, cfg):
            cfg.consensus.peer_gossip_sleep_duration = SLEEP

        nodes, pvs = await _make_net(tmp_path, 2, mutate_cfg=slow_tick)
        try:
            addr_a = pvs[0].address()
            t_signed, t_seen = {}, {}

            def on_a(vote):
                if vote.validator_address == addr_a and vote.type == PREVOTE_TYPE:
                    t_signed.setdefault((vote.height, vote.round), time.perf_counter())

            def on_b(vote):
                if vote.validator_address == addr_a and vote.type == PREVOTE_TYPE:
                    t_seen.setdefault((vote.height, vote.round), time.perf_counter())

            # node0 signs with pvs[0]; on_vote fires when a vote is ADDED
            # to the node's own sets — "lands in the vote set", literally
            nodes[0].consensus.on_vote.append(on_a)
            nodes[1].consensus.on_vote.append(on_b)

            await _wait_all_height(nodes, 3)
            common = sorted(set(t_signed) & set(t_seen))
            assert len(common) >= 2, f"no propagated votes measured: {common}"
            deltas = sorted(t_seen[k] - t_signed[k] for k in common)
            median = deltas[len(deltas) // 2]
            assert median < SLEEP / 3, (
                f"vote propagation {median * 1000:.0f} ms is not meaningfully "
                f"under the {SLEEP * 1000:.0f} ms gossip tick — event wakeups dead?"
            )
            # and the batched wire path actually carried votes
            evs = nodes[0].flight_recorder.events()
            modes = {e.get("mode") for e in evs if e["kind"] == "gossip.votes"}
            assert "batch" in modes, "no vote_batch frames sent on a batched net"
            assert any(e["kind"] == "gossip.wakeup" for e in evs)
        finally:
            await _stop_net(nodes)


class TestMixedVersionInterop:
    async def test_batched_and_legacy_nodes_commit_together(self, tmp_path):
        """One node with gossip_vote_batch forced off (advertises
        gossip_version 0): the net must still commit, with every vote to
        and from the legacy node on the single-vote wire path."""

        def legacy_node2(i, cfg):
            if i == 2:
                cfg.consensus.gossip_vote_batch = False

        nodes, _ = await _make_net(tmp_path, 3, name="mix", mutate_cfg=legacy_node2)
        try:
            assert nodes[0].switch.node_info.gossip_version == GOSSIP_BATCH_VERSION
            assert nodes[2].switch.node_info.gossip_version == 0
            await _wait_all_height(nodes, 3)
            for h in range(1, 4):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"height {h} diverged"

            legacy_prefix = nodes[2].node_key.id[:8]
            # the legacy node never sends batch frames at all...
            n2_modes = {
                e.get("mode")
                for e in nodes[2].flight_recorder.events()
                if e["kind"] == "gossip.votes"
            }
            assert "batch" not in n2_modes and "single" in n2_modes
            # ...and the batched nodes fall back to single-vote frames for
            # it while still batching to each other — the fallback is
            # exercised, not just code-pathed
            for n in nodes[:2]:
                evs = [
                    e for e in n.flight_recorder.events() if e["kind"] == "gossip.votes"
                ]
                to_legacy = {e["mode"] for e in evs if e["peer"] == legacy_prefix}
                assert "batch" not in to_legacy
                assert "single" in to_legacy
                assert any(
                    e["mode"] == "batch" and e["peer"] != legacy_prefix for e in evs
                )
        finally:
            await _stop_net(nodes)


# ---------------------------------------------------------------------------
# mempool sig_precheck (ingress batching satellite)
# ---------------------------------------------------------------------------


class TestMempoolSigPrecheck:
    async def test_burst_of_signed_txs_is_one_engine_flush(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.mempool import Mempool, MempoolError, make_signed_tx

        class _App:
            def __init__(self):
                self.calls = 0

            async def check_tx(self, req):
                self.calls += 1
                return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            app = _App()
            mp = Mempool(app, {"sig_precheck": True})
            mp.sig_verifier = svc
            keys = [Ed25519PrivKey.from_secret(b"tx%d" % i) for i in range(32)]
            txs = [
                make_signed_tx(k, b"burst-key-%d=val" % i)
                for i, k in enumerate(keys)
            ]
            await asyncio.gather(*(mp.check_tx(tx) for tx in txs))
            assert mp.size() == 32 and app.calls == 32
            assert len(cv.calls) == 1 and cv.calls[0] == 32, (
                f"burst should coalesce into one engine flush, got {cv.calls}"
            )
            # a tampered envelope is rejected BEFORE the ABCI round-trip
            bad = bytearray(make_signed_tx(keys[0], b"tampered=1"))
            bad[-1] ^= 0xFF
            with pytest.raises(MempoolError, match="signature"):
                await mp.check_tx(bytes(bad))
            assert app.calls == 32
            # non-envelope txs pass through untouched by the filter
            res = await mp.check_tx(b"plain-key=plain-val")
            assert res.code == abci.CODE_TYPE_OK
        finally:
            await svc.stop()

    async def test_signed_tx_roundtrip(self):
        from tendermint_tpu.mempool import make_signed_tx, parse_signed_tx

        k = Ed25519PrivKey.from_secret(b"roundtrip")
        tx = make_signed_tx(k, b"hello=world")
        pubkey, sign_bytes, sig, payload = parse_signed_tx(tx)
        assert pubkey == k.pub_key().bytes()
        assert payload == b"hello=world"
        assert k.pub_key().verify(sign_bytes, sig)
        assert parse_signed_tx(b"not an envelope") is None
