"""Event-driven batched gossip tests.

The reference gossips one vote / one block part per peer per
`peer_gossip_sleep_duration` tick (consensus/reactor.go:467/606).  This
layer replaces the pacing with per-peer wakeup events and byte-capped
`vote_batch` frames; these tests pin the three contracts that matter:

1. latency — a vote created on node A lands in node B's vote set well
   under the gossip sleep (the wakeup path, not the tick, carries it);
2. batch shape — a received vote_batch reaches the AsyncBatchVerifier as
   exactly ONE flush (one host-prep pass, the engine's batch shape);
3. wire compatibility — a batched node and a legacy single-vote node
   (knob forced off, NodeInfo advertises gossip_version 0) commit blocks
   together, with the fallback path demonstrably exercised.
"""

import asyncio
import time
from types import SimpleNamespace

import pytest

from tendermint_tpu.config import ConsensusConfig, test_config as make_test_cfg
from tendermint_tpu.consensus.reactor import (
    VOTE_CHANNEL,
    ConsensusReactor,
    PeerRoundState,
    _enc,
)
from tendermint_tpu.consensus.types import HeightVoteSet, RoundState
from tendermint_tpu.crypto.batch_verifier import AsyncBatchVerifier, BatchVerifier
from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.encoding import codec
from tendermint_tpu.libs import tracing
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.libs.metrics import ConsensusMetrics
from tendermint_tpu.node import Node
from tendermint_tpu.p2p.node_info import GOSSIP_BATCH_VERSION, NodeInfo
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    PartSetHeader,
    Validator,
    ValidatorSet,
    Vote,
)
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "gossip-test-chain"


# ---------------------------------------------------------------------------
# unit-level fixtures
# ---------------------------------------------------------------------------


class _CountingVerifier(BatchVerifier):
    """Host-path verifier that counts device/host dispatches — one call ==
    one engine flush reached the verify kernel."""

    def __init__(self):
        super().__init__(min_device_batch=10**9)  # always the host path
        self.calls = []

    def start_warmup(self):
        # the host path never dispatches to the device: skip the background
        # bucket compile thread (pure core contention on the CI container)
        return self

    def verify(self, pubkeys, msgs, sigs):
        self.calls.append(len(sigs))
        return super().verify(pubkeys, msgs, sigs)


class _FakeSwitch:
    def __init__(self):
        self.stopped = []

    async def stop_peer_for_error(self, peer, reason):
        self.stopped.append((peer.id, reason))


class _FakeCS:
    """The slice of ConsensusState the reactor's vote-receive path uses."""

    def __init__(self, vset, height=5):
        self.config = ConsensusConfig()
        self.rs = RoundState(
            height=height,
            validators=vset,
            votes=HeightVoteSet(CHAIN_ID, height, vset),
            last_validators=None,
        )
        self.sm_state = SimpleNamespace(chain_id=CHAIN_ID)
        self.on_new_round_step = []
        self.on_vote = []
        self.on_valid_block = []
        self.on_proposal = []
        self.on_new_block_part = []
        self.metrics = ConsensusMetrics()
        self.recorder = tracing.NOP
        self.added = []

    async def add_vote_input(self, vote, peer_id="", verified=False):
        self.added.append((vote, peer_id, verified))


def _vset_and_votes(n=4, height=5, vote_type=PREVOTE_TYPE):
    pvs = [MockPV() for _ in range(n)]
    vset = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
    pvs.sort(key=lambda pv: pv.address())
    votes = []
    for pv in pvs:
        i, _ = vset.get_by_address(pv.address())
        v = Vote(
            type=vote_type, height=height, round=0, block_id=BlockID(),
            timestamp_ns=1, validator_address=pv.address(), validator_index=i,
        )
        pv.sign_vote(CHAIN_ID, v)
        votes.append(v)
    return vset, votes


def _batch_msg(votes):
    return _enc("vote_batch", {"votes": [v.wire() for v in votes]})


class TestVoteWire:
    def test_wire_encode_once_and_roundtrip(self):
        _, votes = _vset_and_votes(2)
        v = votes[0]
        w1 = v.wire()
        assert v.wire() is w1  # cached, not re-encoded
        back = codec.loads(w1)
        assert isinstance(back, Vote)
        assert back == v

    def test_node_info_defaults_legacy_for_old_peers(self):
        # a handshake dict from a node predating the field must resolve to
        # the conservative legacy capability, not the batched one
        old = NodeInfo.from_dict({"node_id": "ab" * 20})
        assert old.gossip_version == 0
        new = NodeInfo.from_dict({"node_id": "ab" * 20, "gossip_version": 1})
        assert new.gossip_version == GOSSIP_BATCH_VERSION


class TestVerifyMany:
    async def test_single_flush_for_whole_batch(self):
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            keys = [Ed25519PrivKey.from_secret(b"vm%d" % i) for i in range(50)]
            msgs = [b"payload-%d" % i for i in range(50)]
            items = [
                (k.pub_key().bytes(), m, k.sign(m)) for k, m in zip(keys, msgs)
            ]
            items[7] = (items[7][0], items[7][1], bytes(64))  # one bad sig
            results = await asyncio.gather(*svc.verify_many(items))
            assert len(cv.calls) == 1 and cv.calls[0] == 50
            assert results[7] is False
            assert all(r for i, r in enumerate(results) if i != 7)
        finally:
            await svc.stop()


class TestVoteBatchReceive:
    async def test_large_batch_rides_the_direct_engine_path(self):
        """Batches >= DIRECT_VERIFY_MIN skip the coalescing flusher and hit
        the engine as ONE direct call (committee-scale hop latency), still
        verified and landed."""
        from tendermint_tpu.consensus.reactor import DIRECT_VERIFY_MIN

        n = DIRECT_VERIFY_MIN + 4
        vset, votes = _vset_and_votes(n)
        cs = _FakeCS(vset)
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            reactor = ConsensusReactor(cs, async_verifier=svc)
            reactor.switch = _FakeSwitch()
            peer = SimpleNamespace(id="direct-peer-000", gossip_version=2)
            reactor.peer_states[peer.id] = PeerRoundState()
            await reactor.receive(VOTE_CHANNEL, peer, _batch_msg(votes))
            assert len(cv.calls) == 1 and cv.calls[0] == n
            assert len(cs.added) == n
            assert all(verified for _, _, verified in cs.added)
        finally:
            await svc.stop()

    async def test_batch_is_one_engine_flush_and_lands_verified(self):
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            reactor = ConsensusReactor(cs, async_verifier=svc)
            reactor.switch = _FakeSwitch()
            peer = SimpleNamespace(id="batch-peer-0000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            await reactor.receive(VOTE_CHANNEL, peer, _batch_msg(votes))
            assert len(cv.calls) == 1 and cv.calls[0] == len(votes), (
                "a vote_batch must reach the engine as exactly one flush"
            )
            assert len(cs.added) == len(votes)
            assert all(verified for _, _, verified in cs.added)
            assert reactor.switch.stopped == []
        finally:
            await svc.stop()

    async def test_bad_signature_in_batch_stops_peer(self):
        vset, votes = _vset_and_votes(4)
        votes[2].signature = bytes(64)
        cs = _FakeCS(vset)
        svc = AsyncBatchVerifier(_CountingVerifier())
        await svc.start()
        try:
            reactor = ConsensusReactor(cs, async_verifier=svc)
            reactor.switch = _FakeSwitch()
            peer = SimpleNamespace(id="badsig-peer-000", gossip_version=1)
            reactor.peer_states[peer.id] = PeerRoundState()
            await reactor.receive(VOTE_CHANNEL, peer, _batch_msg(votes))
            assert reactor.switch.stopped, "invalid batch signature must stop the peer"
            assert cs.added == []
        finally:
            await svc.stop()

    async def test_oversized_batch_stops_peer(self):
        vset, votes = _vset_and_votes(1)
        cs = _FakeCS(vset)
        reactor = ConsensusReactor(cs, async_verifier=None)
        reactor.switch = _FakeSwitch()
        peer = SimpleNamespace(id="flood-peer-0000", gossip_version=1)
        reactor.peer_states[peer.id] = PeerRoundState()
        msg = _enc("vote_batch", {"votes": [votes[0].wire()] * 16385})
        await reactor.receive(VOTE_CHANNEL, peer, msg)
        assert reactor.switch.stopped


class TestRarestFirst:
    def _reactor(self, vset):
        return ConsensusReactor(_FakeCS(vset))

    def test_pick_parts_prefers_parts_fewest_peers_hold(self):
        vset, _ = _vset_and_votes(2)
        reactor = self._reactor(vset)
        header = PartSetHeader(4, b"\x01" * 32)
        ps = PeerRoundState()
        ps.proposal_block_parts_header = header
        ps.proposal_block_parts = BitArray(4)
        other = PeerRoundState()
        other.proposal_block_parts_header = header
        other.proposal_block_parts = BitArray.from_indices(4, [0, 1])
        reactor.peer_states = {"a": ps, "b": other}
        missing = BitArray.from_indices(4, range(4))
        got = reactor._pick_parts(missing, ps, 2)
        # parts 2 and 3 are held by no other peer: they go first
        assert set(got) == {2, 3}
        assert reactor._pick_parts(missing, ps, 10) != []  # window respected
        assert len(reactor._pick_parts(missing, ps, 3)) == 3

    def test_pick_parts_ignores_mismatched_headers(self):
        vset, _ = _vset_and_votes(2)
        reactor = self._reactor(vset)
        ps = PeerRoundState()
        ps.proposal_block_parts_header = PartSetHeader(2, b"\x01" * 32)
        other = PeerRoundState()
        other.proposal_block_parts_header = PartSetHeader(2, b"\x02" * 32)
        other.proposal_block_parts = BitArray.from_indices(2, [0])
        reactor.peer_states = {"a": ps, "b": other}
        missing = BitArray.from_indices(2, range(2))
        assert len(reactor._pick_parts(missing, ps, 2)) == 2


class TestMaj23Dedupe:
    async def test_identical_claim_sent_once_then_expires(self):
        vset, _ = _vset_and_votes(2)
        cs = _FakeCS(vset)
        reactor = ConsensusReactor(cs)
        sent = []

        class _Peer:
            id = "maj23-peer-0000"

            async def send(self, chan, msg):
                sent.append((chan, msg))
                return True

        peer, ps = _Peer(), PeerRoundState()
        bid = BlockID(b"\x05" * 32, PartSetHeader(1, b"\x06" * 32))
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        assert len(sent) == 1, "identical maj23 claim must not be re-sent"
        # a different blockID is a different claim
        bid2 = BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32))
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid2)
        assert len(sent) == 2
        # entries expire so the VoteSetBits repair can re-fire
        key = (5, 0, PREVOTE_TYPE, bid.key())
        ps.maj23_sent[key] -= 10 * cs.config.peer_query_maj23_sleep_duration + 1
        await reactor._maybe_send_maj23(peer, ps, 5, 0, PREVOTE_TYPE, bid)
        assert len(sent) == 3
        # peer height change clears the table
        ps.apply_new_round_step({"height": 6, "round": 0, "step": 1})
        assert ps.maj23_sent == {}


class _CapturePeer:
    """Fake peer capturing every (chan, decoded-kind, raw) send."""

    def __init__(self, pid, gossip_version=2):
        self.id = pid
        self.gossip_version = gossip_version
        self.sent = []

    async def send(self, chan, msg):
        d = codec.loads(msg)
        self.sent.append((chan, d.pop("k"), d, msg))
        return True

    def kinds(self):
        return [k for _, k, _, _ in self.sent]


class TestRelayTopology:
    def _reactor(self, n_peers=10, degree=3, min_peers=2, self_id="ee" * 20):
        vset, _ = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cs.config.gossip_relay_degree = degree
        cs.config.gossip_relay_min_peers = min_peers
        reactor = ConsensusReactor(cs)
        reactor.switch = SimpleNamespace(node_id=self_id, peers={})
        for i in range(n_peers):
            reactor.peer_states[f"{i:02d}" * 20] = PeerRoundState()
        return reactor

    def test_degree_bounded_deterministic_and_rotating(self):
        r = self._reactor()
        t1 = r._relay_targets(5, 0)
        assert t1 is not None and len(t1) == 3
        assert r._relay_targets(5, 0) == t1  # cached + stable
        # an independent reactor with the same peers and identity computes
        # the SAME subset — the selection is a pure function of
        # (height, round, edge ids), the property both endpoints rely on
        assert self._reactor()._relay_targets(5, 0) == t1
        # the subset rotates across rounds: a stuck round re-rolls the graph
        union = set()
        for rnd in range(8):
            union |= r._relay_targets(5, rnd)
        assert len(union) > 3
        # and across heights
        assert any(r._relay_targets(h, 0) != t1 for h in range(6, 10))

    def test_full_mesh_below_thresholds(self):
        assert self._reactor(degree=0)._relay_targets(5, 0) is None
        assert self._reactor(n_peers=4, min_peers=8)._relay_targets(5, 0) is None
        # degree >= peer count: relay pointless, full mesh
        assert self._reactor(n_peers=3, degree=8)._relay_targets(5, 0) is None
        r = self._reactor()
        assert r._relay_ok(next(iter(r._relay_targets(5, 0))))

    def test_peer_churn_invalidates_cache(self):
        r = self._reactor()
        t1 = r._relay_targets(5, 0)
        r.peer_states["ff" * 20] = PeerRoundState()
        r._peer_gen += 1  # what add_peer does
        t2 = r._relay_targets(5, 0)
        assert len(t2) == 3  # recomputed over the new peer set


class TestVoteSummaryFlow:
    async def test_summary_pull_batch_roundtrip(self):
        """The aggregation exchange end to end: A (holds maj23) sends a
        summary instead of streaming votes; B diffs the bitmap and pulls
        exactly what it lacks; A serves one vote_batch; B verifies it as
        ONE engine flush and lands every vote."""
        vset, votes = _vset_and_votes(4)
        cs_a = _FakeCS(vset)
        # aggregation engages only at committee scale, gated exactly like
        # the relay topology (small nets stream votes directly)
        cs_a.config.gossip_relay_degree = 1
        cs_a.config.gossip_relay_min_peers = 1
        for v in votes:
            cs_a.rs.votes.add_vote(v, verify=False)
        vs_a = cs_a.rs.votes.prevotes(0)
        assert vs_a.has_two_thirds_majority()

        reactor_a = ConsensusReactor(cs_a)
        reactor_a.switch = _FakeSwitch()
        peer_b = _CapturePeer("bb" * 20)
        ps_b = PeerRoundState()
        ps_b.height = 5
        reactor_a.peer_states[peer_b.id] = ps_b
        reactor_a.peer_states["ff" * 20] = PeerRoundState()

        # A: maj23 reached -> summary, not a vote stream
        assert await reactor_a._send_votes(peer_b, ps_b, vs_a)
        chan, kind, frame, raw = peer_b.sent[-1]
        assert (chan, kind) == (0x20, "vote_summary")
        assert BitArray.from_bytes(frame["votes"]).count() == 4
        # deduped: an immediate second pass sends nothing new
        assert not await reactor_a._send_votes(peer_b, ps_b, vs_a)
        # ...but a grown bitmap would re-send (count check, not just time)

        # B receives the summary and pulls everything it lacks
        cs_b = _FakeCS(vset)
        reactor_b = ConsensusReactor(cs_b)
        reactor_b.switch = _FakeSwitch()
        peer_a = _CapturePeer("aa" * 20)
        ps_a = PeerRoundState()
        ps_a.height = 5
        reactor_b.peer_states[peer_a.id] = ps_a
        await reactor_b.receive(0x20, peer_a, raw)
        chan, kind, pull, pull_raw = peer_a.sent[-1]
        assert (chan, kind) == (0x23, "vote_pull")
        assert BitArray.from_bytes(pull["want"]).count() == 4
        # the claim was recorded (maj23 machinery feeds VoteSetBits repair)
        assert peer_a.id in cs_b.rs.votes.prevotes(0).peer_maj23s
        # and the belief bits were folded in: B won't stream these back
        assert ps_a.get_vote_bits(5, 0, PREVOTE_TYPE, 4).count() == 4

        # A serves the pull as one byte-capped vote_batch
        await reactor_a.receive(0x23, peer_b, pull_raw)
        chan, kind, batch, batch_raw = peer_b.sent[-1]
        assert (chan, kind) == (0x22, "vote_batch")
        assert len(batch["votes"]) == 4

        # B lands the batch as exactly one engine flush
        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            reactor_b.async_verifier = svc
            await reactor_b.receive(VOTE_CHANNEL, peer_a, batch_raw)
            assert len(cv.calls) == 1 and cv.calls[0] == 4
            assert len(cs_b.added) == 4
            assert all(verified for _, _, verified in cs_b.added)
        finally:
            await svc.stop()

    async def test_summary_only_to_capable_peers(self):
        """A v1 (batch-only) peer must keep getting vote streams — the
        summary exchange is negotiated, not assumed."""
        vset, votes = _vset_and_votes(4)
        cs = _FakeCS(vset)
        for v in votes:
            cs.rs.votes.add_vote(v, verify=False)
        vs = cs.rs.votes.prevotes(0)
        reactor = ConsensusReactor(cs)
        reactor.switch = _FakeSwitch()
        legacy = _CapturePeer("cc" * 20, gossip_version=1)
        ps = PeerRoundState()
        ps.height = 5
        reactor.peer_states[legacy.id] = ps
        assert await reactor._send_votes(legacy, ps, vs)
        assert legacy.kinds() == ["vote_batch"]

    async def test_malformed_summary_and_pull_stop_peer(self):
        vset, _ = _vset_and_votes(4)
        cs = _FakeCS(vset)
        reactor = ConsensusReactor(cs)
        reactor.switch = _FakeSwitch()
        peer = _CapturePeer("dd" * 20)
        reactor.peer_states[peer.id] = PeerRoundState()
        await reactor.receive(0x20, peer, _enc("vote_summary", {
            "height": 5, "round": 0, "type": PREVOTE_TYPE,
            "block_id": {}, "votes": 123,  # not bytes
        }))
        assert reactor.switch.stopped
        reactor.switch.stopped.clear()
        await reactor.receive(0x23, peer, _enc("vote_pull", {
            "height": "x", "round": 0, "type": PREVOTE_TYPE, "want": b"",
        }))
        assert reactor.switch.stopped


class TestPeerStateBounds:
    def test_round_tables_capped(self):
        ps = PeerRoundState()
        ps.height = 5
        for r in range(PeerRoundState.MAX_TRACKED_ROUNDS * 3):
            ps.get_vote_bits(5, r, PREVOTE_TYPE, 4)
        assert len(ps.prevotes) == PeerRoundState.MAX_TRACKED_ROUNDS
        # the newest rounds survive (they are the live ones)
        assert max(ps.prevotes) == PeerRoundState.MAX_TRACKED_ROUNDS * 3 - 1
        assert min(ps.prevotes) == PeerRoundState.MAX_TRACKED_ROUNDS * 2

    def test_round_eviction_refuses_oldest_insert(self):
        """Inserting a round OLDER than a full table must not evict the
        just-inserted entry and then KeyError — it returns None (untracked),
        and live newer rounds survive."""
        ps = PeerRoundState()
        ps.height = 5
        base = 1000
        for r in range(base, base + PeerRoundState.MAX_TRACKED_ROUNDS):
            ps.get_vote_bits(5, r, PREVOTE_TYPE, 4)
        assert ps.get_vote_bits(5, 0, PREVOTE_TYPE, 4) is None
        assert len(ps.prevotes) == PeerRoundState.MAX_TRACKED_ROUNDS
        assert min(ps.prevotes) == base

    def test_vote_set_bits_unresolvable_height_skipped(self):
        """num_validators == 0 (height doesn't pin to a set we hold) must
        not create a permanent zero-size belief entry — set_has_vote on a
        0-bit array is a no-op and every send pass would resend the full
        batch forever."""
        ps = PeerRoundState()
        ps.height = 5
        msg = {"height": 5, "round": 0, "type": PREVOTE_TYPE,
               "votes": BitArray.from_indices(4, [0, 1]).to_bytes()}
        ps.apply_vote_set_bits(msg, None, num_validators=0)
        assert 0 not in ps.prevotes
        ps.apply_vote_set_bits(msg, None, num_validators=4)
        assert ps.prevotes[0].bits == 4

    def test_sent_maps_pruned(self):
        ps = PeerRoundState()
        for i in range(400):
            ps.maj23_sent[(5, i, 1, b"k")] = float(i)  # all "expired"
        ps.prune_sent(ps.maj23_sent, now=1000.0, expired_before=500.0)
        assert len(ps.maj23_sent) <= PeerRoundState.MAX_SENT_ENTRIES
        for i in range(400):
            ps.summary_sent[(5, i, 1)] = (4, 900.0)  # none expired
        ps.prune_sent(ps.summary_sent, now=1000.0, expired_before=500.0)
        assert len(ps.summary_sent) == PeerRoundState.MAX_SENT_ENTRIES

    def test_vote_set_bits_allocation_clamped(self):
        """The wire bitmap's length header is attacker-suppliable; sizing
        a fresh per-round belief array from it let one frame allocate
        gigabytes.  The allocation must clamp to OUR validator count."""
        ps = PeerRoundState()
        ps.height = 5
        huge = (2**31).to_bytes(4, "big") + b"\xff" * 8
        ps.apply_vote_set_bits(
            {"height": 5, "round": 0, "type": PREVOTE_TYPE, "votes": huge},
            None, num_validators=4,
        )
        assert ps.prevotes[0].bits <= 4


# ---------------------------------------------------------------------------
# live-net tests
# ---------------------------------------------------------------------------


def _gen(pvs):
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )


async def _make_net(tmp_path, n, name="g", mutate_cfg=None):
    pvs = sorted([MockPV() for _ in range(n)], key=lambda pv: pv.address())
    gen = _gen(pvs)
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = make_test_cfg(str(tmp_path / f"{name}{i}"))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "memdb"
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.1
        if mutate_cfg is not None:
            mutate_cfg(i, cfg)
        nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))
    for node in nodes:
        await node.start()
    for i in range(n):
        for j in range(i + 1, n):
            addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
            await nodes[i].switch.dial_peer(addr)
    return nodes, pvs


async def _stop_net(nodes):
    for node in nodes:
        if node.is_running:
            await node.stop()


async def _wait_all_height(nodes, h, timeout=45.0):
    async def _wait():
        while not all(n.block_store.height() >= h for n in nodes):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(_wait(), timeout)


class TestEventDrivenLatency:
    async def test_vote_lands_well_under_gossip_sleep(self, tmp_path):
        """Regression for the tentpole claim: with the polling tick cranked
        to 1.5 s, a vote signed on node A must land in node B's vote set in
        a small fraction of that — only the event wakeups can carry it."""
        SLEEP = 1.5

        def slow_tick(i, cfg):
            cfg.consensus.peer_gossip_sleep_duration = SLEEP

        nodes, pvs = await _make_net(tmp_path, 2, mutate_cfg=slow_tick)
        try:
            addr_a = pvs[0].address()
            t_signed, t_seen = {}, {}

            def on_a(vote):
                if vote.validator_address == addr_a and vote.type == PREVOTE_TYPE:
                    t_signed.setdefault((vote.height, vote.round), time.perf_counter())

            def on_b(vote):
                if vote.validator_address == addr_a and vote.type == PREVOTE_TYPE:
                    t_seen.setdefault((vote.height, vote.round), time.perf_counter())

            # node0 signs with pvs[0]; on_vote fires when a vote is ADDED
            # to the node's own sets — "lands in the vote set", literally
            nodes[0].consensus.on_vote.append(on_a)
            nodes[1].consensus.on_vote.append(on_b)

            await _wait_all_height(nodes, 3)
            common = sorted(set(t_signed) & set(t_seen))
            assert len(common) >= 2, f"no propagated votes measured: {common}"
            deltas = sorted(t_seen[k] - t_signed[k] for k in common)
            median = deltas[len(deltas) // 2]
            assert median < SLEEP / 3, (
                f"vote propagation {median * 1000:.0f} ms is not meaningfully "
                f"under the {SLEEP * 1000:.0f} ms gossip tick — event wakeups dead?"
            )
            # and the batched wire path actually carried votes
            evs = nodes[0].flight_recorder.events()
            modes = {e.get("mode") for e in evs if e["kind"] == "gossip.votes"}
            assert "batch" in modes, "no vote_batch frames sent on a batched net"
            assert any(e["kind"] == "gossip.wakeup" for e in evs)
        finally:
            await _stop_net(nodes)


class TestMixedVersionInterop:
    async def test_batched_and_legacy_nodes_commit_together(self, tmp_path):
        """One node with gossip_vote_batch forced off (advertises
        gossip_version 0): the net must still commit, with every vote to
        and from the legacy node on the single-vote wire path."""

        def legacy_node2(i, cfg):
            if i == 2:
                cfg.consensus.gossip_vote_batch = False

        nodes, _ = await _make_net(tmp_path, 3, name="mix", mutate_cfg=legacy_node2)
        try:
            # batch-capable at least (a fully-featured node advertises the
            # summary level on top — capabilities are cumulative)
            assert nodes[0].switch.node_info.gossip_version >= GOSSIP_BATCH_VERSION
            assert nodes[2].switch.node_info.gossip_version == 0
            await _wait_all_height(nodes, 3)
            for h in range(1, 4):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"height {h} diverged"

            legacy_prefix = nodes[2].node_key.id[:8]
            # the legacy node never sends batch frames at all...
            n2_modes = {
                e.get("mode")
                for e in nodes[2].flight_recorder.events()
                if e["kind"] == "gossip.votes"
            }
            assert "batch" not in n2_modes and "single" in n2_modes
            # ...and the batched nodes fall back to single-vote frames for
            # it while still batching to each other — the fallback is
            # exercised, not just code-pathed
            for n in nodes[:2]:
                evs = [
                    e for e in n.flight_recorder.events() if e["kind"] == "gossip.votes"
                ]
                to_legacy = {e["mode"] for e in evs if e["peer"] == legacy_prefix}
                assert "batch" not in to_legacy
                assert "single" in to_legacy
                assert any(
                    e["mode"] == "batch" and e["peer"] != legacy_prefix for e in evs
                )
        finally:
            await _stop_net(nodes)


class TestRelayLiveNet:
    async def test_relay_net_commits_with_summaries(self, tmp_path):
        """5 nodes with the relay topology FORCED on (degree 2 over 4
        peers — event pushes reach half the mesh per round) and summaries
        enabled: the net must still commit and agree, and the maj23
        aggregation path must actually carry state (summaries recorded).
        This is the liveness contract the 100-validator harness scales."""

        def relay_cfg(i, cfg):
            cfg.consensus.gossip_relay_degree = 2
            cfg.consensus.gossip_relay_min_peers = 2

        nodes, _ = await _make_net(tmp_path, 5, name="relay", mutate_cfg=relay_cfg)
        try:
            await _wait_all_height(nodes, 3)
            for h in range(1, 4):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"height {h} diverged"
            kinds = set()
            for n in nodes:
                kinds |= {e["kind"] for e in n.flight_recorder.events()}
            assert "gossip.summary" in kinds, (
                "no vote summaries sent on a relay net that reached maj23"
            )
            assert "gossip.wakeup" in kinds
        finally:
            await _stop_net(nodes)


# ---------------------------------------------------------------------------
# mempool sig_precheck (ingress batching satellite)
# ---------------------------------------------------------------------------


class TestMempoolSigPrecheck:
    async def test_burst_of_signed_txs_is_one_engine_flush(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.mempool import Mempool, MempoolError, make_signed_tx

        class _App:
            def __init__(self):
                self.calls = 0

            async def check_tx(self, req):
                self.calls += 1
                return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

        cv = _CountingVerifier()
        svc = AsyncBatchVerifier(cv)
        await svc.start()
        try:
            app = _App()
            mp = Mempool(app, {"sig_precheck": True})
            mp.sig_verifier = svc
            keys = [Ed25519PrivKey.from_secret(b"tx%d" % i) for i in range(32)]
            txs = [
                make_signed_tx(k, b"burst-key-%d=val" % i)
                for i, k in enumerate(keys)
            ]
            await asyncio.gather(*(mp.check_tx(tx) for tx in txs))
            assert mp.size() == 32 and app.calls == 32
            assert len(cv.calls) == 1 and cv.calls[0] == 32, (
                f"burst should coalesce into one engine flush, got {cv.calls}"
            )
            # a tampered envelope is rejected BEFORE the ABCI round-trip
            bad = bytearray(make_signed_tx(keys[0], b"tampered=1"))
            bad[-1] ^= 0xFF
            with pytest.raises(MempoolError, match="signature"):
                await mp.check_tx(bytes(bad))
            assert app.calls == 32
            # non-envelope txs pass through untouched by the filter
            res = await mp.check_tx(b"plain-key=plain-val")
            assert res.code == abci.CODE_TYPE_OK
        finally:
            await svc.stop()

    async def test_signed_tx_roundtrip(self):
        from tendermint_tpu.mempool import make_signed_tx, parse_signed_tx

        k = Ed25519PrivKey.from_secret(b"roundtrip")
        tx = make_signed_tx(k, b"hello=world")
        pubkey, sign_bytes, sig, payload = parse_signed_tx(tx)
        assert pubkey == k.pub_key().bytes()
        assert payload == b"hello=world"
        assert k.pub_key().verify(sign_bytes, sig)
        assert parse_signed_tx(b"not an envelope") is None


class TestRoundStateReannounce:
    """Liveness repair pinned: NewRoundStep is normally sent only on step
    transitions and add_peer, so a message-level partition (connections
    up, frames dropped) that straddles a height transition leaves both
    sides' PeerRoundState beliefs stale forever — post-heal vote pushes
    then target the wrong height and a healed net stays wedged (measured
    on the forensics rig: Precommit with 2/4 precommits for 70+ s).  The
    maj23 tick now re-announces our round state when it changed since the
    last announce this peer acked, and keeps re-announcing at a slow
    repair cadence while the peer still looks desynced."""

    async def _run_ticks(self, reactor, peer, ps, seconds):
        task = asyncio.ensure_future(reactor._query_maj23_routine(peer, ps))
        try:
            await asyncio.sleep(seconds)
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def test_reannounces_to_desynced_peer_then_dedupes(self):
        vset, _ = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cs.config.peer_query_maj23_sleep_duration = 0.02
        reactor = ConsensusReactor(cs)
        peer = _CapturePeer("reannounce-peer")
        ps = PeerRoundState()  # fresh: believes height 0 — desynced
        await self._run_ticks(reactor, peer, ps, 0.1)
        nrs = [d for _, k, d, _ in peer.sent if k == "new_round_step"]
        assert nrs, "a desynced peer must get our round state re-announced"
        assert nrs[0]["height"] == cs.rs.height
        # value-deduped: several ticks, ONE announce (state unchanged and
        # the send succeeded — no idle chatter on a healthy net)
        assert len(nrs) == 1
        # the peer syncing (applying the announce) keeps it deduped
        ps.apply_new_round_step(nrs[0])
        peer.sent.clear()
        await self._run_ticks(reactor, peer, ps, 0.08)
        assert "new_round_step" not in peer.kinds()
        # our state moving re-arms the announce
        cs.rs.round += 1
        await self._run_ticks(reactor, peer, ps, 0.08)
        assert "new_round_step" in peer.kinds()

    async def test_desynced_peer_gets_slow_cadence_repair_resends(self):
        vset, _ = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cs.config.peer_query_maj23_sleep_duration = 0.01
        reactor = ConsensusReactor(cs)
        peer = _CapturePeer("repair-peer-000")
        ps = PeerRoundState()  # never applies the announce: stays desynced
        await self._run_ticks(reactor, peer, ps, 0.35)
        nrs = [1 for _, k, _, _ in peer.sent if k == "new_round_step"]
        # resend floor is 10 ticks: ~0.35 s of 0.01 s ticks means the
        # stuck-desynced peer saw a few repair re-announces, not a flood
        assert 2 <= len(nrs) <= 5, f"expected slow-cadence resends, got {len(nrs)}"

    async def test_failed_send_is_retried_next_tick(self):
        vset, _ = _vset_and_votes(4)
        cs = _FakeCS(vset)
        cs.config.peer_query_maj23_sleep_duration = 0.02
        reactor = ConsensusReactor(cs)

        class _DropThenOk(_CapturePeer):
            def __init__(self):
                super().__init__("flaky-peer-0000")
                self.fail = 2

            async def send(self, chan, msg):
                if self.fail > 0:
                    self.fail -= 1
                    return False  # partitioned: the frame is dropped
                return await super().send(chan, msg)

        peer = _DropThenOk()
        ps = PeerRoundState()
        await self._run_ticks(reactor, peer, ps, 0.15)
        # dropped announces must not be marked acked — the first
        # SUCCESSFUL send lands as soon as the link heals
        assert "new_round_step" in peer.kinds()
