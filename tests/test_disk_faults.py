"""Storage-fault chaos + self-healing store integrity (ISSUE 15).

Coverage:
  - chaos/disk.py: seeded determinism of every injected fault kind, the
    FaultyDB / FaultyGroup wrappers, torn appends, lying fsyncs + the
    simulated power cut, persistent rot injection
  - scenario DSL: `disk` / `rot` clauses parse, resolve deterministically,
    reject garbage, and drive the InProcRig
  - consensus/wal.py resync: mid-file corruption and multi-record torn
    regions are SKIPPED (with accounting) by the replay path while the
    strict decode stays loud
  - mempool WAL: crc-framed journal + legacy hex-line replay compat
  - store/block_store.py: seal round-trip, legacy entries, quarantine,
    expected-hash fallback chain, restore_block, integrity_scan
  - libs/kvstore.py: batched-write atomicity across injected failures
  - durability discipline: directory fsync after rename in the privval
    atomic write, autofile rotate and the addrbook save
  - clean degradation: ENOSPC inside the consensus receive routine halts
    CLEANLY (attributed, read path alive) — never CONSENSUS FAILURE;
    privval save failure refuses the sign and rolls back
  - self-healing end to end (in-proc net): rot -> scan -> quarantine ->
    peer refill -> load serves the verified block again
"""

import asyncio
import errno
import os
import stat

import pytest

from tendermint_tpu.chaos.disk import (
    DiskFaultTable,
    DiskPolicy,
    FaultyDB,
    FaultyGroup,
    policy_for,
    rot_block_store,
)
from tendermint_tpu.libs.autofile import Group, fsync_dir, walk_frames
from tendermint_tpu.libs.kvstore import MemDB, SQLiteDB
from tendermint_tpu.store import BlockStore
from tendermint_tpu.store.block_store import seal, unseal


# ---------------------------------------------------------------------------
# chaos/disk.py: the fault layer itself
# ---------------------------------------------------------------------------


class TestDiskFaultTable:
    def test_policy_resolution_and_heal(self):
        t = DiskFaultTable(seed=1)
        t.set_policy("blockstore", DiskPolicy(enospc=1.0))
        t.set_policy("*", DiskPolicy(eio=1.0))
        assert t.policy("blockstore").enospc == 1.0
        assert t.policy("wal").eio == 1.0  # wildcard fallback
        t.heal("blockstore")
        assert t.policy("blockstore").eio == 1.0  # back to wildcard
        t.heal()
        assert t.policy("blockstore").is_healthy()

    def test_unknown_store_and_kind_rejected(self):
        t = DiskFaultTable()
        with pytest.raises(ValueError):
            t.set_policy("floppy", DiskPolicy(enospc=1.0))
        with pytest.raises(ValueError):
            policy_for("headcrash")

    def test_enospc_and_eio_raise_honest_errno(self):
        t = DiskFaultTable(seed=2)
        t.set_policy("wal", policy_for("enospc"))
        with pytest.raises(OSError) as ei:
            t.check_write("wal", 100)
        assert ei.value.errno == errno.ENOSPC
        t.set_policy("wal", policy_for("eio"))
        with pytest.raises(OSError) as ei:
            t.check_write("wal", 100)
        assert ei.value.errno == errno.EIO
        assert t.counters()["wal:enospc"] == 1
        assert t.counters()["wal:eio"] == 1

    def test_seeded_probability_sequence_is_deterministic(self):
        def draw(seed):
            t = DiskFaultTable(seed=seed)
            t.set_policy("state", DiskPolicy(enospc=0.5))
            outcomes = []
            for _ in range(40):
                try:
                    t.check_write("state", 10)
                    outcomes.append(0)
                except OSError:
                    outcomes.append(1)
            return outcomes

        assert draw(7) == draw(7)
        assert draw(7) != draw(8)
        assert 0 < sum(draw(7)) < 40  # actually probabilistic

    def test_bitrot_read_flips_exactly_one_bit_deterministically(self):
        def flip(seed):
            t = DiskFaultTable(seed=seed)
            t.set_policy("blockstore", DiskPolicy(bitrot=1.0))
            return t.mangle_read("blockstore", b"\x00" * 64)

        a, b = flip(3), flip(3)
        assert a == b
        diff = [i for i in range(64) if a[i] != 0]
        assert len(diff) == 1
        assert bin(a[diff[0]]).count("1") == 1


class TestFaultyDB:
    def test_write_faults_and_read_rot(self):
        t = DiskFaultTable(seed=4)
        db = FaultyDB(MemDB(), t, "blockstore")
        db.set(b"k", b"v")  # healthy
        t.set_policy("blockstore", policy_for("enospc"))
        with pytest.raises(OSError):
            db.set(b"k2", b"v2")
        with pytest.raises(OSError):
            db.write_batch([(b"k3", b"v3")])
        assert db.inner.get(b"k2") is None  # nothing landed
        t.heal()
        assert db.get(b"k") == b"v"
        t.set_policy("blockstore", DiskPolicy(bitrot=1.0))
        assert db.get(b"k") != b"v"  # transient read damage
        assert db.inner.get(b"k") == b"v"  # cells untouched


class TestFaultyGroup:
    def test_torn_append_cuts_then_raises(self, tmp_path):
        t = DiskFaultTable(seed=5)
        g = FaultyGroup(Group(str(tmp_path / "wal")), t, "wal")
        g.append_record(b"A" * 50)
        g.flush()
        t.set_policy("wal", policy_for("torn"))
        with pytest.raises(OSError):
            g.append_record(b"B" * 50)
        t.heal()
        g.close()
        raw = open(tmp_path / "wal", "rb").read()
        # first record whole, second genuinely cut short on disk
        kinds = [k for k, _, _ in walk_frames(raw)]
        assert kinds[0] == "record" and kinds[-1] == "torn"

    def test_fsync_lie_then_crash_loses_exactly_the_lied_writes(self, tmp_path):
        t = DiskFaultTable(seed=6)
        g = FaultyGroup(Group(str(tmp_path / "wal")), t, "wal")
        g.append_record(b"durable")
        g.sync()  # real fsync: durable watermark advances
        t.set_policy("wal", policy_for("fsync_lie"))
        g.append_record(b"lost-1")
        g.sync()  # lies: reports success, no durability
        g.append_record(b"lost-2")
        g.sync()
        assert g.lied_syncs == 2
        lost = t.simulate_crash()
        assert sum(lost.values()) > 0
        g.close()
        records = [d for k, _, d in walk_frames(open(tmp_path / "wal", "rb").read())
                   if k == "record"]
        assert records == [b"durable"]  # the lied records evaporated cleanly


class TestRot:
    def test_rot_is_persistent_and_seed_deterministic(self, tmp_path):
        from tests.test_types import make_commit, make_test_block

        def build(path):
            db = SQLiteDB(str(path))
            store = BlockStore(db)
            block, vset, pvs = make_test_block(height=1)
            ps = block.make_part_set(1024)
            store.save_block(block, ps, make_commit(vset, pvs, 1, 0, block.block_id(1024)))
            return db, store

        db1, s1 = build(tmp_path / "a.db")
        db2, s2 = build(tmp_path / "b.db")
        i1 = rot_block_store(s1, 1, seed=9)
        i2 = rot_block_store(s2, 1, seed=9)
        assert (i1["offset"], i1["bit"]) == (i2["offset"], i2["bit"])
        # damage survives a reopen (it is in the cells)
        db1.close()
        db1b = SQLiteDB(str(tmp_path / "a.db"))
        store = BlockStore(db1b)
        assert store.load_block(1) is None  # detected, not served
        assert store.quarantined() == [1]
        db1b.close()
        db2.close()


# ---------------------------------------------------------------------------
# scenario DSL
# ---------------------------------------------------------------------------


class TestDiskScenarioDSL:
    def test_disk_and_rot_clauses_parse_and_fingerprint(self):
        from tendermint_tpu.chaos.scenario import Scenario

        text = "disk 2 enospc @5~0.5; disk 2 heal @12; rot 1 blockstore h=3 @8"
        a = Scenario.parse(text, seed=7)
        b = Scenario.parse(text, seed=7)
        assert a.fingerprint() == b.fingerprint()
        actions = [e.action for e in a.timeline()]
        assert sorted(actions) == ["disk", "disk", "rot"]
        disk = next(e for e in a.timeline() if e.action == "disk" and e.args["kind"] == "enospc")
        assert disk.args == {"node": 2, "kind": "enospc", "store": "*", "p": 1.0}
        rot = next(e for e in a.timeline() if e.action == "rot")
        assert rot.args == {"node": 1, "store": "blockstore", "height": 3, "part": 0}

    def test_garbage_disk_clauses_rejected(self):
        from tendermint_tpu.chaos.scenario import Scenario, ScenarioError

        for bad in (
            "disk 2 headcrash @1",
            "disk 2 enospc store=floppy @1",
            "disk 2 enospc q=1 @1",
            "rot 1 statestore h=3 @1",
            "rot 1 blockstore @1",  # missing h=
            "rot 1 blockstore h=x @1",
        ):
            with pytest.raises(ScenarioError):
                Scenario.parse(bad)

    async def test_runner_drives_disk_actions_against_rig(self):
        from tendermint_tpu.chaos.scenario import Scenario, ScenarioRunner

        calls = []

        class _Rig:
            node_count = 3

            async def set_disk(self, i, store, kind, p):
                calls.append(("set", i, store, kind, p))

            async def heal_disk(self, i, store):
                calls.append(("heal", i, store))

            async def rot(self, i, store, height, part):
                calls.append(("rot", i, store, height, part))

        s = Scenario.parse(
            "disk 0 eio store=wal p=0.5 @0; rot 1 blockstore h=2 @0.01; disk 0 heal @0.02"
        )
        await ScenarioRunner(s, _Rig()).run()
        assert calls == [
            ("set", 0, "wal", "eio", 0.5),
            ("rot", 1, "blockstore", 2, 0),
            ("heal", 0, "*"),
        ]


# ---------------------------------------------------------------------------
# WAL resync (consensus) + mempool journal compat
# ---------------------------------------------------------------------------


class TestWALResync:
    def _wal(self, tmp_path, n=6):
        from tendermint_tpu.consensus.wal import WAL

        wal = WAL(str(tmp_path / "cs.wal" / "wal"))
        for h in range(1, n + 1):
            wal.write_sync({"type": "msg", "height": h, "data": b"x" * 120})
        wal.close()
        return str(tmp_path / "cs.wal" / "wal")

    @staticmethod
    def _record_offsets(raw):
        return [pos for kind, pos, _ in walk_frames(raw) if kind == "record"]

    def test_mid_file_corruption_skipped_by_replay_loud_in_strict(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL, WALCorruptionError

        path = self._wal(tmp_path)
        raw = bytearray(open(path, "rb").read())
        offsets = self._record_offsets(bytes(raw))
        raw[offsets[1] + 20] ^= 0xFF  # inside record 2's payload
        open(path, "wb").write(bytes(raw))
        wal = WAL(path)
        with pytest.raises(WALCorruptionError):
            wal.all_records()  # the strict contract stays loud
        records = wal.replay_records()
        heights = [r["height"] for r in records]
        assert heights == [1, 3, 4, 5, 6]
        assert wal.corrupt_regions_skipped == 1
        assert wal.corrupt_bytes_skipped > 0
        wal.close()

    def test_multi_record_corrupt_region_resyncs_once(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        path = self._wal(tmp_path)
        raw = bytearray(open(path, "rb").read())
        offsets = self._record_offsets(bytes(raw))
        # wreck records 2..4: one contiguous region spanning three records
        raw[offsets[1]:offsets[4]] = os.urandom(offsets[4] - offsets[1])
        open(path, "wb").write(bytes(raw))
        wal = WAL(path)
        records = wal.replay_records()
        heights = [r["height"] for r in records]
        assert heights[0] == 1 and heights[-1] == 6
        assert {2, 3, 4}.isdisjoint(heights)
        assert wal.corrupt_regions_skipped >= 1
        wal.close()

    def test_search_for_end_height_survives_corruption(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        path = str(tmp_path / "cs.wal" / "wal")
        wal = WAL(path)
        wal.write_sync({"type": "msg", "height": 1, "data": b"a" * 80})
        wal.write_end_height(1)
        wal.write_sync({"type": "msg", "height": 2, "data": b"b" * 80})
        wal.write_end_height(2)
        wal.write_sync({"type": "msg", "height": 3, "data": b"c" * 80})
        wal.close()
        raw = bytearray(open(path, "rb").read())
        raw[30] ^= 0x55  # corrupt the FIRST record; markers live later
        open(path, "wb").write(bytes(raw))
        wal = WAL(path)
        records, found = wal.search_for_end_height(2)
        assert found
        assert [r["height"] for r in records] == [3]
        wal.close()

    def test_random_resync_never_fabricates_records(self, tmp_path):
        """Tolerant decode invariant: every surviving record is byte-equal
        to SOME original record, in original order (a subsequence) — the
        resync may drop, never invent or reorder."""
        import random

        from tendermint_tpu.consensus.wal import decode_records_resync

        path = self._wal(tmp_path)
        original = open(path, "rb").read()
        full, _ = decode_records_resync(original)
        rng = random.Random(11)
        for _ in range(80):
            raw = bytearray(original)
            for _ in range(rng.randrange(1, 4)):
                op = rng.randrange(3)
                if op == 0:
                    del raw[rng.randrange(1, len(raw)):]
                elif op == 1:
                    raw[rng.randrange(len(raw))] ^= rng.randrange(1, 256)
                else:
                    pos = rng.randrange(len(raw))
                    raw[pos:pos] = bytes(rng.randrange(256) for _ in range(8))
            try:
                got, _rep = decode_records_resync(bytes(raw))
            except Exception:
                continue  # undecodable payload in a colliding frame: loud is fine
            it = iter(full)
            assert all(any(r == f for f in it) for r in got), \
                "resync fabricated or reordered records"


class TestMempoolWALCompat:
    async def _mp(self, tmp_path):
        from tendermint_tpu.abci.examples import KVStoreApplication
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.proxy import local_client_creator

        client = local_client_creator(KVStoreApplication())()
        await client.start()
        mp = Mempool(client, {})
        mp.init_wal(str(tmp_path / "mwal"))
        return client, mp

    def test_legacy_hex_line_journal_still_replays(self, tmp_path):
        from tendermint_tpu.mempool import Mempool

        os.makedirs(tmp_path / "mwal")
        with open(tmp_path / "mwal" / "wal", "wb") as f:
            f.write(b"a=1".hex().encode() + b"\n")
            f.write(b"binary\nwith=newline".hex().encode() + b"\n")
            f.write(b"deadb")  # torn tail (odd hex) ends legacy replay cleanly

        mp = Mempool.__new__(Mempool)  # only the WAL surface is exercised
        mp.storage_health = None
        from tendermint_tpu.libs.autofile import Group

        mp._wal = Group(str(tmp_path / "mwal" / "wal"))
        assert mp.wal_txs() == [b"a=1", b"binary\nwith=newline"]
        mp._wal.close()

    def test_legacy_journal_appended_by_framed_writer_replays_both(self, tmp_path):
        from tendermint_tpu.libs.autofile import Group
        from tendermint_tpu.mempool import Mempool

        os.makedirs(tmp_path / "mwal")
        with open(tmp_path / "mwal" / "wal", "wb") as f:
            f.write(b"old=1".hex().encode() + b"\n")
            f.write(b"old=2".hex().encode() + b"\n")
        mp = Mempool.__new__(Mempool)
        mp.storage_health = None
        mp._wal = Group(str(tmp_path / "mwal" / "wal"))
        mp._wal.append_record(b"new=1")  # post-upgrade framed append
        mp._wal.flush()
        assert mp.wal_txs() == [b"old=1", b"old=2", b"new=1"]
        mp._wal.close()

    async def test_corrupt_region_skipped_rest_replays(self, tmp_path):
        client, mp = await self._mp(tmp_path)
        try:
            await mp.check_tx(b"a=1")
            await mp.check_tx(b"b=2")
            await mp.check_tx(b"c=3")
            mp._wal.flush()
            path = mp._wal.head_path
            raw = bytearray(open(path, "rb").read())
            offsets = [pos for k, pos, _ in walk_frames(bytes(raw)) if k == "record"]
            raw[offsets[1] + 9] ^= 0xFF  # wreck the middle of record 2
            open(path, "wb").write(bytes(raw))
            txs = mp.wal_txs()
            assert b"a=1" in txs and b"c=3" in txs  # resync recovered the rest
            assert b"b=2" not in txs
        finally:
            mp.close_wal()
            await client.stop()


# ---------------------------------------------------------------------------
# block store: seal, quarantine, scan, restore
# ---------------------------------------------------------------------------


def _saved_store(db, height=1):
    from tests.test_types import make_commit, make_test_block

    block, vset, pvs = make_test_block(height=height)
    store = BlockStore(db)
    ps = block.make_part_set(1024)
    store.save_block(block, ps, make_commit(vset, pvs, height, 0, block.block_id(1024)))
    return store, block


class TestSeal:
    def test_roundtrip_and_corruption_detection(self):
        payload = b"payload-bytes"
        sealed = seal(payload)
        assert unseal(sealed) == (payload, False)
        broken = bytearray(sealed)
        broken[-1] ^= 1
        assert unseal(bytes(broken)) == (None, True)
        # legacy (unsealed) values pass through untouched
        assert unseal(payload) == (payload, False)
        assert unseal(None) == (None, False)


class TestStoreIntegrity:
    def test_legacy_unsealed_entries_still_load(self):
        """A store written by the pre-seal format must keep serving: strip
        the seals off every entry and reload."""
        db = MemDB()
        store, block = _saved_store(db)
        for k in list(db._data):
            payload, corrupt = unseal(db.get(k))
            assert not corrupt
            db.set(k, payload)  # rewrite unsealed (the old format)
        store2 = BlockStore(db)
        assert store2.load_block(1).hash() == block.hash()
        assert store2.integrity_scan()["corrupt"] == []

    def test_rot_detected_quarantined_never_served(self):
        db = MemDB()
        store, block = _saved_store(db)
        rot_block_store(store, 1, seed=1)
        assert store.load_block(1) is None
        assert store.quarantined() == [1]
        assert store.load_block_part(1, 0) is None  # quarantine gates parts too
        # the identity survives for the refill
        assert store.quarantine_expected_hash(1) == block.hash()

    def test_legacy_entry_rot_caught_by_block_hash_check(self):
        """Bit-rot in an UNSEALED (legacy) part has no crc to fail — the
        reassembled-hash check must catch it instead."""
        db = MemDB()
        store, block = _saved_store(db)
        key = b"P:1:0"
        payload, _ = unseal(db.get(key))
        db.set(key, payload)  # legacy format
        raw = bytearray(db.get(key))
        # flip a byte INSIDE the part's content (codec payload region)
        raw[len(raw) // 2] ^= 0x01
        db.set(key, bytes(raw))
        assert store.load_block(1) is None
        assert store.quarantined() == [1]

    def test_integrity_scan_detects_and_reports(self):
        db = MemDB()
        store, block = _saved_store(db)
        report = store.integrity_scan()
        assert report["corrupt"] == [] and report["checked"] == 1
        rot_block_store(store, 1, seed=2)
        report = store.integrity_scan()
        assert report["corrupt"] == [1]
        assert report["quarantined"] == [1]
        assert store.last_scan is report

    def test_quarantine_survives_reopen(self):
        db = MemDB()
        store, _ = _saved_store(db)
        store.quarantine(1, "test")
        store2 = BlockStore(db)
        assert store2.quarantined() == [1]
        assert store2.load_block(1) is None

    def test_expected_hash_fallback_chain(self):
        """Meta rotted too: the commit / next-header identities must still
        recover the expected hash."""
        db = MemDB()
        store, block = _saved_store(db)
        # wreck the meta entry beyond recognition
        db.set(b"H:1", b"\xc5\x1f" + b"\x00\x00\x00\x00" + b"garbage")
        assert store.quarantine_expected_hash(1) == block.hash()  # via SC:1

    def test_restore_block_refills_and_lifts_quarantine(self):
        db = MemDB()
        store, block = _saved_store(db)
        rot_block_store(store, 1, seed=3)
        assert store.load_block(1) is None and store.quarantined() == [1]
        store.restore_block(1, block)  # the "peer copy"
        assert store.quarantined() == []
        assert store.load_block(1).hash() == block.hash()
        assert store.integrity_scan()["corrupt"] == []

    def test_restore_block_rejects_wrong_block(self):
        from tests.test_types import make_test_block

        db = MemDB()
        store, block = _saved_store(db)
        rot_block_store(store, 1, seed=4)
        assert store.load_block(1) is None  # detection quarantines
        imposter, _, _ = make_test_block(height=1, txs=[b"evil"])
        with pytest.raises(ValueError, match="expected"):
            store.restore_block(1, imposter)
        assert store.quarantined() == [1]  # still quarantined


class TestKVStoreBatchAtomicity:
    def test_memdb_batch_all_or_nothing(self):
        db = MemDB()
        db.set(b"x", b"old")

        def bad_iter():
            yield (b"x", b"new")
            raise RuntimeError("boom mid-batch")

        with pytest.raises(RuntimeError):
            db.write_batch(bad_iter())
        assert db.get(b"x") == b"old"  # nothing applied

    def test_sqlite_commit_failure_rolls_back_whole_batch(self, tmp_path):
        """Simulated fsync/commit failure mid-batch: afterwards NONE of
        the batch may be visible — a set_sync batch observed half-applied
        after a crash is a bug (and without an explicit rollback the next
        unrelated commit would flush the half-applied statements)."""
        db = SQLiteDB(str(tmp_path / "kv.db"))
        db.set(b"x", b"old")

        real = db._conn

        class FailingCommit:
            def __init__(self, conn):
                self._conn = conn
                self.fail = True

            def __getattr__(self, name):
                return getattr(self._conn, name)

            def commit(self):
                if self.fail:
                    self.fail = False
                    raise OSError(errno.EIO, "injected commit failure")
                return self._conn.commit()

        db._conn = FailingCommit(real)
        with pytest.raises(OSError):
            db.write_batch([(b"x", b"new"), (b"y", b"1")], deletes=[b"z"])
        db._conn = real
        assert db.get(b"x") == b"old"
        assert db.get(b"y") is None
        # the connection is still usable for the next write
        db.set(b"k", b"v")
        assert db.get(b"k") == b"v"
        db.close()


class TestCommitRotHealing:
    """Commit entries have no content of their own to refill — their
    carrier is block h+1's last_commit.  Rot in one sibling repairs from
    the other IN PLACE; rot in both quarantines the CARRIER height (whose
    refill rewrites the canonical commit), never the intact block h."""

    def _wreck(self, db, key):
        db.set(key, b"\xc5\x1f" + b"\x00\x00\x00\x00" + b"garbage")

    def test_canonical_rot_repairs_from_seen_commit(self):
        db = MemDB()
        store, block = _saved_store(db)
        good = store.load_seen_commit(1)
        self._wreck(db, b"C:1")
        # block 1 itself must stay servable — its content is intact
        repaired = store.load_block_commit(1)
        assert repaired is not None and repaired.height == good.height
        assert store.quarantined() == []
        assert store.load_block(1) is not None
        # the repair landed on disk: a fresh store reads it clean
        assert BlockStore(db).load_block_commit(1) is not None

    def test_seen_rot_repairs_from_canonical(self):
        db = MemDB()
        store, _ = _saved_store(db)
        # C:1 only exists once block 2 lands; seed it from the seen commit
        payload, _ = unseal(db.get(b"SC:1"))
        db.set(b"C:1", seal(payload))
        self._wreck(db, b"SC:1")
        assert store.load_seen_commit(1) is not None
        assert store.quarantined() == []

    def test_both_rotted_quarantines_the_carrier_height(self):
        from tests.test_types import make_commit, make_test_block

        db = MemDB()
        store, b1 = _saved_store(db)
        # grow to height 2 so C:1 has a carrier in range
        b2, vset, pvs = make_test_block(height=2)
        b2.last_commit = make_commit(vset, pvs, 1, 0, b1.block_id(1024))
        ps = b2.make_part_set(1024)
        store.save_block(b2, ps, make_commit(vset, pvs, 2, 0, b2.block_id(1024)))
        self._wreck(db, b"C:1")
        self._wreck(db, b"SC:1")
        assert store.load_block_commit(1) is None
        assert store.quarantined() == [2]  # the CARRIER, not the intact block 1
        assert store.load_block(1) is not None
        # refill of the carrier restores the canonical commit for 1
        store.restore_block(2, b2)
        assert store.quarantined() == []
        assert store.load_block_commit(1) is not None

    def test_scan_repairs_commits_in_place(self):
        db = MemDB()
        store, _ = _saved_store(db)
        self._wreck(db, b"C:1")
        payload, _ = unseal(db.get(b"SC:1"))
        assert payload is not None  # sibling intact -> repairable
        report = store.integrity_scan()
        assert report["corrupt"] == []  # block content fine
        assert report["repaired_commits"] == [1]
        assert store.quarantined() == []
        assert BlockStore(db).load_block_commit(1) is not None


class TestQuarantineHookAndGating:
    def test_lazy_read_detection_fires_refill_hook(self):
        """Rot discovered by a LOAD (not a scan) must still queue the
        height for peer refill — the hook fires on every quarantine."""
        db = MemDB()
        store, _ = _saved_store(db)
        kicked = []
        store.on_quarantine = kicked.append
        rot_block_store(store, 1, seed=6)
        assert store.load_block(1) is None
        assert kicked == [1]

    def test_hook_failure_never_breaks_the_load_path(self):
        db = MemDB()
        store, _ = _saved_store(db)
        store.on_quarantine = lambda h: (_ for _ in ()).throw(RuntimeError("boom"))
        rot_block_store(store, 1, seed=7)
        assert store.load_block(1) is None  # still answers None, no raise
        assert store.quarantined() == [1]


class TestStorageFaultClassification:
    def test_only_storage_errnos_classify(self):
        from tendermint_tpu.consensus.state import _is_storage_fault

        assert _is_storage_fault(OSError(errno.ENOSPC, "full"))
        assert _is_storage_fault(OSError(errno.EIO, "io"))
        # a socket ABCI app dying is an OSError too — but NOT disk forensics
        assert not _is_storage_fault(ConnectionResetError(errno.ECONNRESET, "reset"))
        assert not _is_storage_fault(OSError(errno.EPIPE, "pipe"))
        assert not _is_storage_fault(OSError())  # errno-less
        assert not _is_storage_fault(RuntimeError("not even an OSError"))


class TestUnsolicitedBlockResponse:
    async def test_steady_state_drops_before_deserialize(self, monkeypatch):
        """A peer streaming unsolicited block_response at a caught-up node
        must not cost a multi-MB deserialize per message."""
        import tendermint_tpu.fastsync.reactor as fr
        from tendermint_tpu.fastsync.reactor import BLOCKCHAIN_CHANNEL, BlockchainReactor, _enc

        class _State:
            last_block_height = 5

        reactor = BlockchainReactor.__new__(BlockchainReactor)
        reactor.fast_sync = False
        reactor.refill_heights = set()
        reactor.block_store = None
        reactor.reporter = None

        def trap(raw):
            raise AssertionError("deserialized an unsolicited block in steady state")

        monkeypatch.setattr(fr.Block, "deserialize", trap)
        await reactor.receive(
            BLOCKCHAIN_CHANNEL, None, _enc("block_response", {"block": b"x" * 1024})
        )


# ---------------------------------------------------------------------------
# durability discipline: directory fsync after rename
# ---------------------------------------------------------------------------


class _FsyncRecorder:
    """Monkeypatch target for os.fsync recording whether each synced fd
    was a DIRECTORY — the crash-simulation pin for the rename+dirsync
    discipline."""

    def __init__(self, real):
        self.real = real
        self.dir_syncs = 0
        self.file_syncs = 0

    def __call__(self, fd):
        if stat.S_ISDIR(os.fstat(fd).st_mode):
            self.dir_syncs += 1
        else:
            self.file_syncs += 1
        return self.real(fd)


class TestDirFsyncDiscipline:
    def test_privval_atomic_write_fsyncs_directory(self, tmp_path, monkeypatch):
        from tendermint_tpu.privval.file import _atomic_write_json

        rec = _FsyncRecorder(os.fsync)
        monkeypatch.setattr(os, "fsync", rec)
        _atomic_write_json(str(tmp_path / "state.json"), {"height": 1})
        assert rec.file_syncs >= 1, "file content must be fsynced"
        assert rec.dir_syncs >= 1, (
            "rename without a directory fsync can LOSE the whole file on "
            "power loss — a double-sign vector for the last-sign state"
        )

    def test_group_rotate_fsyncs_directory(self, tmp_path, monkeypatch):
        g = Group(str(tmp_path / "wal"), head_size_limit=16)
        g.write(b"Z" * 64)
        rec = _FsyncRecorder(os.fsync)
        monkeypatch.setattr(os, "fsync", rec)
        g.maybe_rotate()
        g.close()
        assert rec.dir_syncs >= 1
        assert os.path.exists(str(tmp_path / "wal.000"))

    def test_addrbook_save_fsyncs_directory(self, tmp_path, monkeypatch):
        from tendermint_tpu.p2p.pex import AddrBook

        book = AddrBook(str(tmp_path / "addrbook.json"))
        rec = _FsyncRecorder(os.fsync)
        monkeypatch.setattr(os, "fsync", rec)
        book.save()
        assert rec.dir_syncs >= 1

    def test_fsync_dir_survives_unsyncable_dir(self, monkeypatch):
        # best-effort contract: refusal to open/sync a dir must not raise
        fsync_dir("/nonexistent-dir-xyz/file")


# ---------------------------------------------------------------------------
# privval: refuse-the-sign discipline under persistence failure
# ---------------------------------------------------------------------------


class TestPrivvalPersistenceFailure:
    def _pv(self, tmp_path):
        from tendermint_tpu.privval.file import FilePV

        return FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"))

    def _vote(self, h=1, r=0):
        from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
        from tendermint_tpu.types.vote import Vote

        return Vote(
            type=PRECOMMIT_TYPE, height=h, round=r,
            validator_address=b"\x01" * 20, validator_index=0,
            timestamp_ns=1_700_000_000_000_000_000,
        )

    def test_save_failure_refuses_sign_and_rolls_back(self, tmp_path, monkeypatch):
        import tendermint_tpu.privval.file as pvfile

        pv = self._pv(tmp_path)
        pv.save()

        def deny(path, obj):
            raise OSError(errno.ENOSPC, "no space left on device")

        monkeypatch.setattr(pvfile, "_atomic_write_json", deny)
        vote = self._vote()
        with pytest.raises(OSError):
            pv.sign_vote("chain", vote)
        assert vote.signature == b"", "no signature may escape an unpersisted sign"
        lss = pv.last_sign_state
        assert (lss.height, lss.round, lss.step) == (0, 0, 0), \
            "in-memory state must roll back on failed persist"
        # disk heals -> the SAME HRS signs fine (no phantom conflict)
        monkeypatch.undo()
        vote2 = self._vote()
        pv.sign_vote("chain", vote2)
        assert vote2.signature != b""
        assert lss.height == 1

    def test_state_file_never_torn_by_failed_save(self, tmp_path, monkeypatch):
        """An injected failure DURING the atomic write leaves the previous
        state file byte-intact (tempfile + rename atomicity)."""
        pv = self._pv(tmp_path)
        pv.sign_vote("chain", self._vote(h=1))
        before = open(tmp_path / "state.json", "rb").read()

        real_replace = os.replace

        def deny(src, dst):
            raise OSError(errno.EIO, "injected")

        monkeypatch.setattr(os, "replace", deny)
        with pytest.raises(OSError):
            pv.sign_vote("chain", self._vote(h=2))
        monkeypatch.setattr(os, "replace", real_replace)
        assert open(tmp_path / "state.json", "rb").read() == before


# ---------------------------------------------------------------------------
# watchdog disk detectors + checker served-block invariant
# ---------------------------------------------------------------------------


class TestWatchdogDiskAlarms:
    def _node_with_health(self, tmp_path=None):
        from tendermint_tpu.libs.watchdog import StorageHealth

        class _N:
            pass

        n = _N()
        n.storage_health = StorageHealth(
            data_dir=str(tmp_path) if tmp_path is not None else None
        )
        return n

    def test_disk_fault_fires_on_write_error_and_clears_after_hold(self):
        import time as _time

        from tendermint_tpu.libs.watchdog import Watchdog

        node = self._node_with_health()
        wd = Watchdog(node, disk_fault_hold=30.0)
        now = _time.monotonic()
        health = wd.check(now=now)
        assert "disk_fault" not in health["alarms"]
        node.storage_health.note_write_error("wal", OSError(errno.ENOSPC, "full"))
        health = wd.check(now=_time.monotonic())
        assert health["alarms"]["disk_fault"]["severity"] == "critical"
        assert health["verdict"] == "critical"
        # past the hold window with no new faults: clears
        health = wd.check(now=_time.monotonic() + 31.0)
        assert "disk_fault" not in health["alarms"]

    def test_halt_is_sticky(self):
        import time as _time

        from tendermint_tpu.libs.watchdog import Watchdog

        node = self._node_with_health()
        node.storage_health.note_halt("consensus", "storage fault (ENOSPC)")
        wd = Watchdog(node)
        health = wd.check(now=_time.monotonic() + 10_000.0)
        assert "disk_fault" in health["alarms"]
        assert "halted" in health["alarms"]["disk_fault"]["reason"]

    def test_disk_pressure_on_low_free_bytes(self, tmp_path):
        import time as _time

        from tendermint_tpu.libs.watchdog import Watchdog

        node = self._node_with_health(tmp_path)
        free = node.storage_health.free_bytes()
        assert free is not None and free > 0
        wd = Watchdog(node, disk_free_bytes=free * 2)  # threshold above reality
        health = wd.check(now=_time.monotonic())
        assert health["alarms"]["disk_pressure"]["severity"] == "degraded"
        wd2 = Watchdog(node, disk_free_bytes=1)  # plenty of headroom
        health = wd2.check(now=_time.monotonic())
        assert "disk_pressure" not in health["alarms"]

    def test_quarantine_and_scan_feed_summary(self):
        node = self._node_with_health()
        sh = node.storage_health
        sh.note_quarantine("blockstore", 3, "integrity scan")
        sh.note_scan({"checked": 10, "corrupt": [3], "quarantined": [3], "ms": 1.2})
        sh.note_refill("blockstore", 3)
        s = sh.summary()
        assert s["refills"] == 1
        assert s["quarantined"]["blockstore"] == 0
        assert s["last_scan"]["corrupt"] == [3]


class TestCheckerServedCorruption:
    def test_served_corrupt_block_is_violation(self):
        from tendermint_tpu.chaos.checker import InvariantChecker

        c = InvariantChecker(2)
        c.observe_served_block(0, 5, b"\xaa" * 32, b"\xaa" * 32)
        assert c.ok()
        c.observe_served_block(1, 5, b"\xaa" * 32, b"\xbb" * 32)
        assert not c.ok()
        assert "SERVED a corrupted block" in c.violations[0]

    def test_served_block_feeds_agreement(self):
        from tendermint_tpu.chaos.checker import InvariantChecker

        c = InvariantChecker(2)
        c.observe_served_block(0, 5, b"\xaa" * 32, b"\xaa" * 32)
        c.observe_served_block(1, 5, b"\xcc" * 32, b"\xcc" * 32)
        assert not c.ok()  # the two claims disagree at height 5


# ---------------------------------------------------------------------------
# clean degradation + self-healing, end to end (in-proc)
# ---------------------------------------------------------------------------


class TestCleanHaltOnStorageFault:
    async def test_enospc_halts_consensus_cleanly_read_path_alive(self, tmp_path, capfd):
        """ENOSPC on the block store inside the receive routine: consensus
        must halt ATTRIBUTED (halted_reason, storage_health) with the read
        path alive — never escape as CONSENSUS FAILURE with undefined
        state (the same class as PR 9's NotEnoughVotingPowerError escape)."""
        from tendermint_tpu.config import test_config as make_test_cfg
        from tendermint_tpu.node import Node
        from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
        from tendermint_tpu.types.params import BlockParams, ConsensusParams

        pv = MockPV()
        gen = GenesisDoc(
            chain_id="disk-halt-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
            consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        )
        cfg = make_test_cfg(str(tmp_path / "halt"))
        cfg.rpc.laddr = ""
        cfg.chaos.enabled = True
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.02
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        await node.start()
        try:
            while node.block_store.height() < 2:
                await asyncio.sleep(0.02)
            node.disk_faults.set_policy("blockstore", policy_for("enospc"))
            await asyncio.wait_for(node.consensus.wait_done(), 30.0)
            assert node.consensus.halted_reason is not None
            assert "ENOSPC" in node.consensus.halted_reason
            # the read path serves on: history loads fine
            assert node.block_store.load_block(1) is not None
            # fault reached the health sink -> disk_fault alarm (critical)
            assert node.storage_health.halts.get("consensus")
            from tendermint_tpu.libs.watchdog import Watchdog

            health = Watchdog(node).check()
            assert health["alarms"]["disk_fault"]["severity"] == "critical"
            out = capfd.readouterr()
            assert "CONSENSUS FAILURE" not in out.out + out.err
        finally:
            await node.stop()


class TestSelfHealingRefill:
    async def test_rot_scan_quarantine_refill_from_peers(self, tmp_path):
        """The tentpole proof, in-proc: seeded bit-rot in one node's block
        store is detected by the integrity scan, quarantined, re-fetched
        from peers through the fastsync channel, verified against the
        surviving identity and served again — while the node keeps
        committing at the tip."""
        from tests.test_consensus_net import make_net, stop_net, wait_all_height

        nodes, pvs = await make_net(tmp_path, 4, name="heal")
        try:
            await wait_all_height(nodes, 4)
            victim = nodes[1]
            good_hash = victim.block_store.load_block(2).hash()
            rot_block_store(victim.block_store, 2, seed=5)
            report = victim.block_store.integrity_scan()
            assert report["corrupt"] == [2]
            assert victim.block_store.load_block(2) is None  # never served corrupt
            victim.blockchain_reactor.request_refill(report["quarantined"])

            async def healed():
                while victim.block_store.load_block(2) is None:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(healed(), 20.0)
            assert victim.block_store.load_block(2).hash() == good_hash
            assert victim.block_store.quarantined() == []
            assert victim.blockchain_reactor.refilled == 1
            # the net kept committing through the heal
            tip = max(n.block_store.height() for n in nodes)
            await wait_all_height(nodes, tip + 1, timeout=20.0)
        finally:
            await stop_net(nodes)
