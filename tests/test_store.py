"""Storage tests: kvstore backends, block store, state store, tx indexer.

Coverage model: store/store_test.go, state/store_test.go,
state/txindex/kv/kv_test.go.
"""

import pytest

from tendermint_tpu.libs.kvstore import MemDB, SQLiteDB
from tendermint_tpu.state import State, StateStore, make_genesis_state
from tendermint_tpu.state.txindex import TxIndexer
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    GenesisDoc,
    GenesisValidator,
    MockPV,
    Validator,
)
from tendermint_tpu.types.tx import tx_hash

from tests.test_types import CHAIN_ID, make_commit, make_test_block


@pytest.fixture(params=["memdb", "sqlite"])
def db(request, tmp_path):
    if request.param == "memdb":
        yield MemDB()
    else:
        d = SQLiteDB(str(tmp_path / "kv.db"))
        yield d
        d.close()


class TestKVStore:
    def test_roundtrip_and_prefix(self, db):
        db.set(b"a/1", b"v1")
        db.set(b"a/2", b"v2")
        db.set(b"b/1", b"v3")
        assert db.get(b"a/1") == b"v1"
        assert db.get(b"missing") is None
        assert [(k, v) for k, v in db.iterate_prefix(b"a/")] == [
            (b"a/1", b"v1"),
            (b"a/2", b"v2"),
        ]
        db.delete(b"a/1")
        assert db.get(b"a/1") is None

    def test_write_batch(self, db):
        db.set(b"x", b"old")
        db.write_batch([(b"x", b"new"), (b"y", b"1")], deletes=[b"z"])
        assert db.get(b"x") == b"new"
        assert db.get(b"y") == b"1"


class TestBlockStore:
    def _saved_store(self, db):
        block, vset, pvs = make_test_block(height=1)
        store = BlockStore(db)
        ps = block.make_part_set(1024)
        seen = make_commit(vset, pvs, 1, 0, block.block_id(1024))
        store.save_block(block, ps, seen)
        return store, block, vset, pvs

    def test_save_load_roundtrip(self, db):
        store, block, _, _ = self._saved_store(db)
        assert store.height() == 1
        assert store.base() == 1
        loaded = store.load_block(1)
        assert loaded.hash() == block.hash()
        meta = store.load_block_meta(1)
        assert meta.block_id.hash == block.hash()
        assert meta.num_txs == len(block.txs)
        assert store.load_block_by_hash(block.hash()).hash() == block.hash()
        seen = store.load_seen_commit(1)
        assert seen.height == 1
        part = store.load_block_part(1, 0)
        assert part is not None and part.index == 0
        # reopening from the same DB restores height bookkeeping
        store2 = BlockStore(db)
        assert store2.height() == 1 and store2.base() == 1

    def test_wrong_height_rejected(self, db):
        store, block, vset, pvs = self._saved_store(db)
        b3, _, _ = make_test_block(height=3)
        ps = b3.make_part_set(1024)
        with pytest.raises(ValueError, match="expected"):
            store.save_block(b3, ps, make_commit(vset, pvs, 3, 0, b3.block_id(1024)))

    def test_missing_heights(self, db):
        store = BlockStore(db)
        assert store.load_block(5) is None
        assert store.load_block_meta(5) is None
        assert store.height() == 0 and store.size() == 0


class TestStateStore:
    def _gen_doc(self, n=4):
        pvs = [MockPV() for _ in range(n)]
        return GenesisDoc(
            chain_id=CHAIN_ID,
            validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        )

    def test_genesis_state(self, db):
        store = StateStore(db)
        state = store.load_from_db_or_genesis(self._gen_doc())
        assert state.chain_id == CHAIN_ID
        assert state.last_block_height == 0
        assert state.validators.size() == 4
        assert state.last_validators.size() == 0

    def test_save_load_roundtrip(self, db):
        store = StateStore(db)
        state = make_genesis_state(self._gen_doc())
        store.save(state)
        loaded = store.load()
        assert loaded.equals(state)
        # validators stored for heights 1 and 2
        v1 = store.load_validators(1)
        assert v1 is not None and v1.hash() == state.validators.hash()
        v2 = store.load_validators(2)
        assert v2 is not None
        params = store.load_consensus_params(1)
        assert params == state.consensus_params

    def test_validator_pointer_scheme(self, db):
        # unchanged sets store pointer records; the full set only at
        # last_changed (state/store.go:295 LoadValidators)
        store = StateStore(db)
        state = make_genesis_state(self._gen_doc())
        store.save(state)
        # simulate 3 committed heights with no validator changes
        for h in range(1, 4):
            s = state.copy()
            s.last_block_height = h
            s.last_validators = s.validators.copy()
            s.validators = s.next_validators.copy()
            s.next_validators = s.next_validators.copy_increment_proposer_priority(1)
            state = s
            store.save(state)
        v4 = store.load_validators(4)
        assert v4 is not None
        assert v4.hash() == state.next_validators.hash()

    def test_abci_responses(self, db):
        store = StateStore(db)
        responses = {
            "deliver_txs": [{"code": 0, "data": b"ok"}],
            "end_block": {"validator_updates": []},
        }
        store.save_abci_responses(7, responses)
        assert store.load_abci_responses(7) == responses
        assert store.load_abci_responses(8) is None


class TestTxIndexer:
    def test_index_get_search(self, db):
        idx = TxIndexer(db)
        tx = b"tx-payload"
        idx.index(
            {"height": 5, "index": 0, "tx": tx, "result": {"code": 0}},
            events={"transfer.sender": ["alice"], "transfer.amount": ["100"]},
        )
        idx.index(
            {"height": 6, "index": 0, "tx": b"other", "result": {"code": 0}},
            events={"transfer.sender": ["bob"]},
        )
        got = idx.get(tx_hash(tx))
        assert got["height"] == 5 and got["tx"] == tx

        assert len(idx.search("transfer.sender='alice'")) == 1
        assert len(idx.search("tx.height=5")) == 1
        assert len(idx.search("tx.height>4")) == 2
        assert len(idx.search("transfer.sender='alice' AND tx.height=5")) == 1
        assert idx.search("transfer.sender='carol'") == []
