"""Crypto tests: ed25519 host+reference paths, secp256k1, multisig, merkle."""

import hashlib
import os

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.crypto.keys import (
    Ed25519PrivKey,
    Ed25519PubKey,
    Secp256k1PrivKey,
    pubkey_from_dict,
)
from tendermint_tpu.crypto.merkle import (
    hash_from_byte_slices,
    proofs_from_byte_slices,
)
from tendermint_tpu.crypto.multisig import (
    MultisigThresholdPubKey,
    build_multisig_signature,
)
from tendermint_tpu.libs.bitarray import BitArray


def test_ed25519_sign_verify():
    priv = Ed25519PrivKey.from_secret(b"seed")
    pub = priv.pub_key()
    msg = b"hello tendermint"
    sig = priv.sign(msg)
    assert pub.verify(msg, sig)
    assert not pub.verify(msg + b"!", sig)
    assert not pub.verify(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    assert len(pub.address()) == 20
    assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]


def test_ed25519_pure_python_matches_host():
    priv = Ed25519PrivKey.from_secret(b"oracle")
    pub = priv.pub_key()
    for i in range(5):
        msg = os.urandom(40) + bytes([i])
        sig = priv.sign(msg)
        assert em.verify(pub.bytes(), msg, sig)
        bad = bytearray(sig)
        bad[0] ^= 1
        assert not em.verify(pub.bytes(), msg, bytes(bad))
        assert em.verify(pub.bytes(), msg, sig) == pub.verify(msg, sig)


def test_ed25519_decompress_roundtrip():
    priv = Ed25519PrivKey.generate()
    pt = em.decompress(priv.pub_key().bytes())
    assert pt is not None
    x, y = pt
    assert em.compress(x, y) == priv.pub_key().bytes()
    # on-curve check: -x^2 + y^2 = 1 + d x^2 y^2
    lhs = (-x * x + y * y) % em.P
    rhs = (1 + em.D * x * x % em.P * y * y) % em.P
    assert lhs == rhs


def test_ed25519_noncanonical_s_rejected():
    priv = Ed25519PrivKey.from_secret(b"s-check")
    pub = priv.pub_key()
    msg = b"msg"
    sig = priv.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    bad_s = (s + em.L).to_bytes(32, "little")  # same point, non-canonical
    assert not pub.verify(msg, sig[:32] + bad_s)
    assert not em.verify(pub.bytes(), msg, sig[:32] + bad_s)


def test_double_scalar_mult_matches_naive():
    A = em.scalar_mult(12345, em.BASE)
    got = em.double_scalar_mult(7, A, 9)
    want = em.point_add(em.scalar_mult(7, A), em.scalar_mult(9, em.BASE))
    assert em.to_affine(got) == em.to_affine(want)


def test_secp256k1():
    priv = Secp256k1PrivKey.generate()
    pub = priv.pub_key()
    msg = b"abc transaction"
    sig = priv.sign(msg)
    assert len(sig) == 64
    assert pub.verify(msg, sig)
    assert not pub.verify(b"other", sig)
    assert len(pub.address()) == 20
    # high-S rejected
    from tendermint_tpu.crypto.keys import _SECP_N

    s = int.from_bytes(sig[32:], "big")
    high = _SECP_N - s
    assert not pub.verify(msg, sig[:32] + high.to_bytes(32, "big"))


def test_multisig_threshold():
    privs = [Ed25519PrivKey.from_secret(bytes([i])) for i in range(4)]
    pub = MultisigThresholdPubKey(2, [p.pub_key() for p in privs])
    msg = b"multisig msg"
    bits = BitArray.from_indices(4, [1, 3])
    sigs = [privs[1].sign(msg), privs[3].sign(msg)]
    sig = build_multisig_signature(bits, sigs)
    assert pub.verify(msg, sig)
    # below threshold
    bits1 = BitArray.from_indices(4, [1])
    assert not pub.verify(msg, build_multisig_signature(bits1, [sigs[0]]))
    # wrong signer position
    bits2 = BitArray.from_indices(4, [0, 3])
    assert not pub.verify(msg, build_multisig_signature(bits2, sigs))
    # roundtrip through dict
    pub2 = pubkey_from_dict(pub.to_dict())
    assert pub2.verify(msg, sig)
    assert pub2.address() == pub.address()


def test_merkle_root_and_proofs():
    items = [b"a", b"b", b"c", b"d", b"e"]
    root = hash_from_byte_slices(items)
    root2, proofs = proofs_from_byte_slices(items)
    assert root == root2
    for i, p in enumerate(proofs):
        assert p.verify(root, items[i])
        assert not p.verify(root, b"wrong")
    # empty & single
    assert hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    r1, p1 = proofs_from_byte_slices([b"only"])
    assert p1[0].verify(r1, b"only")


def test_merkle_known_structure():
    # two leaves: root = inner(leaf(a), leaf(b))
    la = hashlib.sha256(b"\x00a").digest()
    lb = hashlib.sha256(b"\x00b").digest()
    assert hash_from_byte_slices([b"a", b"b"]) == hashlib.sha256(b"\x01" + la + lb).digest()


class TestXChaCha20Poly1305:
    """crypto/xchacha20poly1305/vector_test.go — draft-irtf-cfrg-xchacha-03
    vectors."""

    def test_hchacha20_vector(self):
        from tendermint_tpu.crypto.xchacha20poly1305 import hchacha20

        key = bytes(range(32))
        nonce16 = bytes.fromhex("000000090000004a0000000031415927")
        assert hchacha20(key, nonce16).hex() == (
            "82413b4227b27bfed30e42508a877d73"
            "a0f9e4d58a74a853c12ec41326d3ecdc"
        )

    def test_aead_vector_and_roundtrip(self):
        from tendermint_tpu.crypto.xchacha20poly1305 import XChaCha20Poly1305

        pt = (
            b"Ladies and Gentlemen of the class of '99: If I could offer you "
            b"only one tip for the future, sunscreen would be it."
        )
        aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
        key = bytes.fromhex(
            "808182838485868788898a8b8c8d8e8f909192939495969798999a9b9c9d9e9f"
        )
        nonce = bytes.fromhex("404142434445464748494a4b4c4d4e4f5051525354555657")
        aead = XChaCha20Poly1305(key)
        ct = aead.seal(nonce, pt, aad)
        assert ct[:16].hex() == "bd6d179d3e83d43b9576579493c0e939"
        assert ct[-16:].hex() == "c0875924c1c7987947deafd8780acf49"
        assert aead.open(nonce, ct, aad) == pt
        # tamper detection
        bad = ct[:-1] + bytes([ct[-1] ^ 1])
        import pytest as _pytest

        with _pytest.raises(Exception):
            aead.open(nonce, bad, aad)


class TestArmor:
    """crypto/armor/armor_test.go."""

    def test_roundtrip_with_headers(self):
        from tendermint_tpu.crypto.armor import decode_armor, encode_armor

        data = os.urandom(200)
        s = encode_armor("TENDERMINT PRIVATE KEY", {"kdf": "bcrypt", "salt": "abcd"}, data)
        bt, headers, out = decode_armor(s)
        assert bt == "TENDERMINT PRIVATE KEY"
        assert headers == {"kdf": "bcrypt", "salt": "abcd"}
        assert out == data

    def test_corrupt_checksum_rejected(self):
        from tendermint_tpu.crypto.armor import decode_armor, encode_armor

        s = encode_armor("TEST BLOCK", {}, b"payload-bytes")
        lines = s.splitlines()
        # flip a base64 body char
        body_idx = next(i for i, ln in enumerate(lines) if ln and not ln.startswith("-") and ":" not in ln and not ln.startswith("="))
        ln = lines[body_idx]
        lines[body_idx] = ("B" if ln[0] != "B" else "C") + ln[1:]
        import pytest as _pytest

        with _pytest.raises(ValueError):
            decode_armor("\n".join(lines))
