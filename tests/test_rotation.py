"""Dynamic validator-set rotation: the engine/consensus seams a set
change crosses.

Pins the pieces the rotation smoke exercises end-to-end, at unit scale:
ValidatorSet.update_with_change_set edge cases at N=100 (removing the
current proposer, priority re-centering), the TableCache rebuild pipeline
(recorder event + prometheus counter + a post-rotation commit verifying
through the engine's indexed path), fold_commit flipping aggregation on
and off as the set migrates, evidence from a validator already rotated
out of the set (unbonding-window semantics via historical sets), the
scenario-DSL valset clauses, and RotatingPV key activation.
"""

import time

import pytest

from tendermint_tpu.crypto import batch as batch_hook
from tendermint_tpu.crypto.batch_verifier import BatchVerifier, TableCache
from tendermint_tpu.libs.kvstore import MemDB
from tendermint_tpu.libs.tracing import FlightRecorder
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.state.validation import verify_evidence
from tendermint_tpu.types import (
    PRECOMMIT_TYPE,
    DuplicateVoteEvidence,
    MockPV,
    Validator,
    ValidatorSet,
    VoteSet,
)
from tendermint_tpu.types.params import ConsensusParams, EvidenceParams
from tests.test_types import (
    CHAIN_ID,
    make_block_id,
    make_commit,
    rand_validator_set,
    signed_vote,
)


# -- update_with_change_set at N=100 ----------------------------------------


class TestUpdateWithChangeSet:
    def test_removing_current_proposer_at_n100(self):
        vset, pvs = rand_validator_set(100)
        vset.increment_proposer_priority(1)
        proposer = vset.get_proposer()
        vset.update_with_change_set([Validator.new(proposer.pub_key, 0)])
        assert vset.size() == 99
        assert not vset.has_address(proposer.address)
        new_proposer = vset.get_proposer()
        assert new_proposer is not None
        assert new_proposer.address != proposer.address
        # the cached proposer pointer must be live (a member), not stale
        assert vset.has_address(new_proposer.address)

    def test_priorities_recentered_after_churn_at_n100(self):
        vset, pvs = rand_validator_set(100)
        vset.increment_proposer_priority(37)
        # remove 10, add 10, double 10
        changes = [Validator.new(pv.get_pub_key(), 0) for pv in pvs[:10]]
        changes += [Validator.new(MockPV().get_pub_key(), 10) for _ in range(10)]
        changes += [Validator.new(pv.get_pub_key(), 20) for pv in pvs[10:20]]
        vset.update_with_change_set(changes)
        assert vset.size() == 100
        # re-centering: average priority ~0 (Go-truncation rounding slack)
        prios = [v.proposer_priority for v in vset.validators]
        assert abs(sum(prios)) < len(prios)
        # rescaling: spread bounded by the priority window
        from tendermint_tpu.types.validator import PRIORITY_WINDOW_SIZE_FACTOR

        assert max(prios) - min(prios) <= (
            PRIORITY_WINDOW_SIZE_FACTOR * vset.total_voting_power()
        )
        # rotation still works after the churn
        seen = set()
        for _ in range(100):
            vset.increment_proposer_priority(1)
            seen.add(vset.get_proposer().address)
        assert len(seen) > 50  # every-ish validator gets turns, no wedge

    def test_updated_proposer_power_reflected_in_cached_pointer(self):
        vset, pvs = rand_validator_set(4)
        vset.increment_proposer_priority(1)
        proposer = vset.get_proposer()
        _, pv = next(
            (i, p) for i, p in enumerate(pvs) if p.address() == proposer.address
        )
        vset.update_with_change_set([Validator.new(pv.get_pub_key(), 99)])
        again = vset.get_proposer()
        if again.address == proposer.address:
            assert again.voting_power == 99  # not the stale pre-update object

    def test_membership_change_rotates_pubkeys_digest(self):
        vset, _ = rand_validator_set(4)
        before = vset.pubkeys_digest()
        vset.update_with_change_set([Validator.new(MockPV().get_pub_key(), 10)])
        assert vset.pubkeys_digest() != before


# -- TableCache rebuild pipeline --------------------------------------------


class TestTableRebuild:
    def _engine(self):
        rec = FlightRecorder(size=256)
        from prometheus_client import CollectorRegistry

        from tendermint_tpu.libs.metrics import VerifyMetrics

        reg = CollectorRegistry()
        verifier = BatchVerifier(
            min_device_batch=1 << 30,  # host tier: no device compiles in tests
            metrics=VerifyMetrics(reg, CHAIN_ID),
            recorder=rec,
        )
        return verifier, rec, reg

    def _wait_table(self, cache, key, budget=30.0):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            if cache.has_table(key):
                return
            time.sleep(0.02)
        raise AssertionError("table rebuild never completed")

    def test_rebuild_fires_recorder_event_and_counter(self):
        verifier, rec, reg = self._engine()
        cache = TableCache(verifier, tabulated=False)
        vset, _ = rand_validator_set(5)
        key = vset.pubkeys_digest()
        rows = [v.pub_key.bytes() for v in vset.validators]
        assert cache.rebuild(key, rows) is True
        self._wait_table(cache, key)
        events = [e for e in rec.events() if e["kind"] == "verify.table_rebuild"]
        assert len(events) == 1
        ev = events[0]
        assert ev["ok"] is True
        assert ev["validators"] == 5
        assert ev["set_key"] == key.hex()[:16]
        assert (
            reg.get_sample_value(
                "tendermint_verify_table_rebuilds_total", {"chain_id": CHAIN_ID}
            )
            == 1.0
        )
        # second rebuild for the same set is a no-op (already cached)
        assert cache.rebuild(key, rows) is False

    def test_post_rotation_commit_verifies_through_engine_path(self):
        """The acceptance pin: after a set change, a commit signed by the
        NEW set must verify through the rebuilt table (the engine's
        indexed hook), not the cold fallback."""
        verifier, rec, _ = self._engine()
        cache = TableCache(verifier, tabulated=False)
        vset, pvs = rand_validator_set(4)
        bid = make_block_id()

        # rotate: drop one validator, add two — the set the next commit uses
        joiners = [MockPV() for _ in range(2)]
        vset.update_with_change_set(
            [Validator.new(pvs[0].get_pub_key(), 0)]
            + [Validator.new(pv.get_pub_key(), 10) for pv in joiners]
        )
        new_pvs = sorted(pvs[1:] + joiners, key=lambda pv: pv.address())
        new_key = vset.pubkeys_digest()
        assert cache.rebuild(
            new_key, [v.pub_key.bytes() for v in vset.validators]
        )
        self._wait_table(cache, new_key)

        commit = make_commit(vset, new_pvs, 7, 0, bid)
        hits_before = [
            e for e in rec.events() if e["kind"] == "verify.table" and e["hit"]
        ]
        try:
            batch_hook.set_indexed_verifier(cache.verify_indexed)
            vset.verify_commit(CHAIN_ID, bid, 7, commit)
        finally:
            batch_hook.set_indexed_verifier(None)
        hits_after = [
            e for e in rec.events() if e["kind"] == "verify.table" and e["hit"]
        ]
        assert len(hits_after) == len(hits_before) + 1  # served by the table


# -- BLS aggregation flipping with set composition --------------------------


class TestAggregationFlip:
    def _bls_set(self, n, power=10):
        pytest.importorskip("tendermint_tpu.crypto.bls.keys")
        from tendermint_tpu.crypto.bls.keys import BlsPrivKey

        pvs = [MockPV(BlsPrivKey.from_secret(bytes([i + 1]) * 32)) for i in range(n)]
        vset = ValidatorSet([Validator.new(pv.get_pub_key(), power) for pv in pvs])
        pvs.sort(key=lambda pv: pv.address())
        return vset, pvs

    def test_fold_engages_on_uniform_and_disengages_on_mixed(self):
        from tendermint_tpu.types.agg_commit import fold_commit, set_is_uniform_bls

        vset, pvs = self._bls_set(4)
        assert set_is_uniform_bls(vset)
        bid = make_block_id()
        commit = make_commit(vset, pvs, 9, 0, bid)
        agg = fold_commit(commit, vset, CHAIN_ID)
        assert agg is not None
        assert len(agg.agg_sig) == 96
        # ONE pairing authenticates the folded commit against the set
        vset.verify_commit(CHAIN_ID, bid, 9, agg)

        # mid-chain flip: one member rotates back to ed25519 — the set is
        # no longer uniform and folding must disengage
        ed = MockPV()
        mixed = vset.copy()
        mixed.update_with_change_set(
            [Validator.new(pvs[0].get_pub_key(), 0), Validator.new(ed.get_pub_key(), 10)]
        )
        assert not set_is_uniform_bls(mixed)
        mixed_pvs = sorted(pvs[1:] + [ed], key=lambda pv: pv.address())
        mixed_commit = make_commit(mixed, mixed_pvs, 10, 0, bid)
        assert fold_commit(mixed_commit, mixed, CHAIN_ID) is None
        # the classic path still verifies the mixed-set commit
        mixed.verify_commit(CHAIN_ID, bid, 10, mixed_commit)

    def test_catchup_agg_commit_authenticated_against_historical_set(self):
        """A laggard replaying a folded height verifies the stored
        AggregateCommit against the set AT THAT HEIGHT (loaded through
        the state store), not whatever set is current."""
        from tendermint_tpu.types.agg_commit import fold_commit

        vset, pvs = self._bls_set(4)
        bid = make_block_id()
        commit = make_commit(vset, pvs, 9, 0, bid)
        agg = fold_commit(commit, vset, CHAIN_ID)

        store = StateStore(MemDB())
        sets = []
        store._stage_validators(sets, 9, 9, vset)
        store.db.write_batch(sets)
        historical = store.load_validators(9)
        assert historical is not None and historical.hash() == vset.hash()
        historical.verify_commit(CHAIN_ID, bid, 9, agg)

        # a DIFFERENT set (post-rotation) must reject the same aggregate
        other, _ = self._bls_set(4, power=7)
        other_members = ValidatorSet(
            [Validator.new(MockPV().get_pub_key(), 10) for _ in range(4)]
        )
        with pytest.raises(ValueError):
            other_members.verify_commit(CHAIN_ID, bid, 9, agg)


# -- evidence across set changes (unbonding window) --------------------------


class TestEvidenceAcrossRotation:
    UNBONDING_BLOCKS = 20

    def _setup(self, evidence_height, current_height):
        """Validator set A (with the byzantine validator) active at
        evidence_height; the validator has since rotated out — the CURRENT
        set does not contain it."""
        vset, pvs = rand_validator_set(4)
        culprit = pvs[0]
        now_ns = time.time_ns()

        store = StateStore(MemDB())
        sets = []
        store._stage_validators(sets, evidence_height, evidence_height, vset)
        store.db.write_batch(sets)

        current = vset.copy()
        current.update_with_change_set([Validator.new(culprit.get_pub_key(), 0)])
        state = State(
            chain_id=CHAIN_ID,
            last_block_height=current_height,
            last_block_time_ns=now_ns,
            validators=current,
            next_validators=current.copy(),
            last_validators=current.copy(),
            consensus_params=ConsensusParams(
                evidence=EvidenceParams(
                    max_age_num_blocks=self.UNBONDING_BLOCKS,
                    max_age_duration_ns=3600 * 1_000_000_000,
                )
            ),
        )
        va = signed_vote(
            culprit, vset, PRECOMMIT_TYPE, evidence_height, 0, make_block_id(b"\x01"),
            ts=now_ns,
        )
        vb = signed_vote(
            culprit, vset, PRECOMMIT_TYPE, evidence_height, 0, make_block_id(b"\x02"),
            ts=now_ns,
        )
        ev = DuplicateVoteEvidence.from_votes(culprit.get_pub_key(), va, vb)
        return state, store, ev

    def test_departed_validator_accepted_inside_unbonding_window(self):
        from tendermint_tpu.evidence import EvidencePool

        state, store, ev = self._setup(
            evidence_height=10, current_height=10 + self.UNBONDING_BLOCKS - 3
        )
        # the culprit is NOT in the current set — only the historical one
        assert not state.validators.has_address(ev.address())
        pool = EvidencePool(MemDB(), store, state)
        pool.add_evidence(ev)
        assert pool.is_pending(ev)
        assert pool.num_pending() == 1

    def test_departed_validator_rejected_beyond_unbonding_window(self):
        state, store, ev = self._setup(
            evidence_height=10, current_height=10 + self.UNBONDING_BLOCKS + 1
        )
        with pytest.raises(ValueError, match="too old"):
            verify_evidence(state, ev, store)

    def test_rejected_when_no_historical_set_stored(self):
        state, store, ev = self._setup(
            evidence_height=10, current_height=12
        )
        empty_store = StateStore(MemDB())
        with pytest.raises(ValueError, match="no validator set stored"):
            verify_evidence(state, ev, empty_store)

    def test_never_a_validator_rejected_even_inside_window(self):
        state, store, ev = self._setup(evidence_height=10, current_height=12)
        outsider = MockPV()
        stranger_set, s_pvs = rand_validator_set(2)
        va = signed_vote(
            s_pvs[0], stranger_set, PRECOMMIT_TYPE, 10, 0, make_block_id(b"\x01"),
            ts=state.last_block_time_ns,
        )
        vb = signed_vote(
            s_pvs[0], stranger_set, PRECOMMIT_TYPE, 10, 0, make_block_id(b"\x02"),
            ts=state.last_block_time_ns,
        )
        bogus = DuplicateVoteEvidence.from_votes(s_pvs[0].get_pub_key(), va, vb)
        with pytest.raises(ValueError, match="not a validator"):
            verify_evidence(state, bogus, store)


# -- scenario DSL valset clauses --------------------------------------------


class TestValsetDSL:
    def test_parse_all_ops(self):
        from tendermint_tpu.chaos.scenario import Scenario

        s = Scenario.parse(
            "valset join 4 power=20 @1\n"
            "valset leave 2 @2\n"
            "valset power 1=50 @3\n"
            "valset migrate 0 bls @4\n"
            "valset migrate 3 ed25519 @5",
            seed=1,
        )
        ops = [e.args for e in s.timeline() if e.action == "valset"]
        assert ops[0] == {"op": "join", "node": 4, "power": 20}
        assert ops[1] == {"op": "leave", "node": 2}
        assert ops[2] == {"op": "power", "node": 1, "power": 50}
        # "bls" normalizes to the canonical scheme name
        assert ops[3] == {"op": "migrate", "node": 0, "scheme": "bls12381"}
        assert ops[4] == {"op": "migrate", "node": 3, "scheme": "ed25519"}

    def test_join_defaults_power(self):
        from tendermint_tpu.chaos.scenario import Scenario

        s = Scenario.parse("valset join 1 @0", seed=1)
        assert s.timeline()[0].args["power"] == 10

    def test_parse_rejections(self):
        from tendermint_tpu.chaos.scenario import Scenario, ScenarioError

        for text in (
            "valset join 1 power=0 @0",       # non-positive power
            "valset join 1 speed=9 @0",       # unknown key
            "valset migrate 0 rsa @0",        # unknown scheme
            "valset bogus 1 @0",              # unknown op
            "valset @0",                      # missing op
        ):
            with pytest.raises(ScenarioError):
                Scenario.parse(text, seed=1)

    def test_fingerprint_covers_valset_clauses(self):
        from tendermint_tpu.chaos.scenario import Scenario

        a = Scenario.parse("valset join 1 power=10 @0", seed=1)
        b = Scenario.parse("valset join 1 power=20 @0", seed=1)
        assert a.fingerprint() != b.fingerprint()


# -- RotatingPV --------------------------------------------------------------


class TestRotatingPV:
    def test_activates_candidate_in_observed_set(self):
        from tendermint_tpu.types import RotatingPV

        ed, ed2 = MockPV(), MockPV()
        pv = RotatingPV(ed, ed2)
        assert pv.get_pub_key() == ed.get_pub_key()  # candidate 0 pre-rotation

        vset = ValidatorSet([Validator.new(ed2.get_pub_key(), 10)])
        pv.observe_validators(vset)
        assert pv.get_pub_key() == ed2.get_pub_key()

        # a set containing NEITHER key keeps the current signer
        other = ValidatorSet([Validator.new(MockPV().get_pub_key(), 10)])
        pv.observe_validators(other)
        assert pv.get_pub_key() == ed2.get_pub_key()

        # rotating back
        back = ValidatorSet([Validator.new(ed.get_pub_key(), 10)])
        pv.observe_validators(back)
        assert pv.get_pub_key() == ed.get_pub_key()

    def test_signs_with_active_candidate(self):
        from tendermint_tpu.types import RotatingPV

        ed, ed2 = MockPV(), MockPV()
        pv = RotatingPV(ed, ed2)
        vset = ValidatorSet([Validator.new(ed2.get_pub_key(), 10)])
        pv.observe_validators(vset)
        vote = signed_vote(pv, vset, PRECOMMIT_TYPE, 3, 0, make_block_id())
        vote.verify(CHAIN_ID, ed2.get_pub_key())  # raises on mismatch
        with pytest.raises(Exception):
            vote.verify(CHAIN_ID, ed.get_pub_key())

    def test_requires_a_candidate(self):
        from tendermint_tpu.types import RotatingPV

        with pytest.raises(ValueError):
            RotatingPV()
