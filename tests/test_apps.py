"""Bank + staking application unit tests.

The bank app is the contended-state workload backend (nonces, fees,
overdrafts); the staking app extends it with validator records whose
end_block updates drive live set rotation.  These tests pin the tx
grammar, the rejection codes, the end_block update emission (including
the PoP gate on BLS rotations), epoch barrel-shift determinism, and
record persistence across an app restart.
"""

import json

import pytest

from tendermint_tpu.abci import types as t
from tendermint_tpu.apps.bank import (
    BankApplication,
    CODE_BAD_NONCE,
    CODE_BAD_SIG,
    CODE_INSUFFICIENT_FUNDS,
    CODE_MALFORMED,
    CODE_OK,
    DEFAULT_FAUCET,
    make_transfer_tx,
)
from tendermint_tpu.apps.staking import (
    CODE_BAD_POP,
    CODE_KEY_IN_USE,
    CODE_NO_VALIDATOR,
    StakingApplication,
    make_bond_tx,
    make_edit_power_tx,
    make_rotate_key_tx,
    make_unbond_tx,
)
from tendermint_tpu.crypto.keys import Ed25519PrivKey
from tendermint_tpu.libs.kvstore import MemDB


def _key(seed: int) -> Ed25519PrivKey:
    return Ed25519PrivKey.from_secret(bytes([seed]) * 32)


def _addr(priv) -> bytes:
    return priv.pub_key().address()


def _block(app, height, *txs):
    """Run txs through one begin/deliver/end/commit cycle; returns
    (deliver responses, end_block validator updates)."""
    app.begin_block(t.RequestBeginBlock())
    res = [app.deliver_tx(t.RequestDeliverTx(tx=tx)) for tx in txs]
    eb = app.end_block(t.RequestEndBlock(height=height))
    app.commit()
    return res, eb.validator_updates


# -- bank -------------------------------------------------------------------


def test_bank_transfer_moves_balance_and_debits_fee():
    app = BankApplication()
    a, b = _key(1), _key(2)
    (res,), _ = _block(app, 1, make_transfer_tx(a, _addr(b), 100, 0, fee=7))
    assert res.code == CODE_OK
    assert app._account(_addr(a)) == (DEFAULT_FAUCET - 107, 1)
    assert app._account(_addr(b)) == (DEFAULT_FAUCET + 100, 0)
    assert app.fee_pool == 7


def test_bank_nonces_strictly_sequential():
    app = BankApplication()
    a, b = _key(1), _key(2)
    replay = make_transfer_tx(a, _addr(b), 1, 0)
    (r0,), _ = _block(app, 1, replay)
    assert r0.code == CODE_OK
    # replaying nonce 0 and skipping to nonce 2 both fail; nonce 1 works
    assert app.deliver_tx(t.RequestDeliverTx(tx=replay)).code == CODE_BAD_NONCE
    skip = make_transfer_tx(a, _addr(b), 1, 2)
    assert app.deliver_tx(t.RequestDeliverTx(tx=skip)).code == CODE_BAD_NONCE
    ok = make_transfer_tx(a, _addr(b), 1, 1)
    assert app.deliver_tx(t.RequestDeliverTx(tx=ok)).code == CODE_OK


def test_bank_overdraft_rejected_checktx_and_delivertx():
    app = BankApplication(faucet=50)
    a, b = _key(1), _key(2)
    tx = make_transfer_tx(a, _addr(b), 51, 0)
    assert app.check_tx(t.RequestCheckTx(tx=tx)).code == CODE_INSUFFICIENT_FUNDS
    assert app.deliver_tx(t.RequestDeliverTx(tx=tx)).code == CODE_INSUFFICIENT_FUNDS
    # fee counts against the same balance
    tx2 = make_transfer_tx(a, _addr(b), 45, 0, fee=6)
    assert app.deliver_tx(t.RequestDeliverTx(tx=tx2)).code == CODE_INSUFFICIENT_FUNDS


def test_bank_delivertx_verifies_signature():
    app = BankApplication()
    a, b = _key(1), _key(2)
    tx = bytearray(make_transfer_tx(a, _addr(b), 10, 0))
    tx[-1] ^= 0x01  # corrupt the payload after signing
    assert app.deliver_tx(t.RequestDeliverTx(tx=bytes(tx))).code == CODE_BAD_SIG


def test_bank_malformed_payloads_rejected():
    app = BankApplication()
    a = _key(1)
    from tendermint_tpu.mempool import make_signed_tx

    for payload in (b"bank:send:zz:1:0", b"bank:mint:00:1:0", b"noise"):
        tx = make_signed_tx(a, payload)
        assert app.deliver_tx(t.RequestDeliverTx(tx=tx)).code == CODE_MALFORMED
    assert app.deliver_tx(t.RequestDeliverTx(tx=b"raw bytes")).code == CODE_MALFORMED


def test_bank_self_transfer_conserves_balance():
    app = BankApplication()
    a = _key(1)
    (res,), _ = _block(app, 1, make_transfer_tx(a, _addr(a), 500, 0))
    assert res.code == CODE_OK
    assert app._account(_addr(a)) == (DEFAULT_FAUCET, 1)


def test_bank_apphash_deterministic_across_replicas():
    txs = [
        make_transfer_tx(_key(1), _addr(_key(2)), 10, 0, fee=1),
        make_transfer_tx(_key(2), _addr(_key(3)), 20, 0),
        make_transfer_tx(_key(1), _addr(_key(3)), 30, 1),
    ]
    hashes = []
    for _ in range(2):
        app = BankApplication()
        _block(app, 1, *txs)
        hashes.append(app.app_hash)
    assert hashes[0] == hashes[1] and hashes[0]


def test_bank_genesis_state_seeds_accounts_and_faucet():
    app = BankApplication()
    rich = _addr(_key(9))
    state = json.dumps(
        {"bank": {"faucet": 5, "accounts": {rich.hex(): 12345}}}
    ).encode()
    app.init_chain(t.RequestInitChain(app_state_bytes=state))
    assert app.faucet == 5
    assert app._account(rich) == (12345, 0)
    assert app._account(_addr(_key(8))) == (5, 0)  # lazy faucet uses override


def test_bank_query_paths():
    app = BankApplication()
    a, b = _key(1), _key(2)
    _block(app, 1, make_transfer_tx(a, _addr(b), 10, 0, fee=3))
    q = app.query(t.RequestQuery(path="balance", data=_addr(a)))
    assert q.code == t.CODE_TYPE_OK and int(q.value) == DEFAULT_FAUCET - 13
    q = app.query(t.RequestQuery(path="nonce", data=_addr(a)))
    assert int(q.value) == 1
    q = app.query(t.RequestQuery(path="fee_pool"))
    assert int(q.value) == 3
    assert app.query(t.RequestQuery(path="nope")).code != t.CODE_TYPE_OK


# -- staking ----------------------------------------------------------------


def _genesis_update(priv, power) -> t.ValidatorUpdate:
    return t.ValidatorUpdate(
        pub_key_type="ed25519", pub_key=priv.pub_key().bytes(), power=power
    )


def test_staking_init_chain_registers_genesis_validators():
    app = StakingApplication()
    g = _key(1)
    app.init_chain(
        t.RequestInitChain(
            validators=[_genesis_update(g, 10)],
            app_state_bytes=json.dumps({"staking": {"epoch_length": 16}}).encode(),
        )
    )
    assert app.epoch_length == 16
    rec = app.validators[_addr(g)]  # owner = the consensus key's address
    assert rec["power"] == 10 and rec["pub_key"] == g.pub_key().bytes()


def test_staking_bond_joins_and_emits_update():
    app = StakingApplication()
    owner = _key(5)
    (res,), updates = _block(app, 1, make_bond_tx(owner, 40, 0))
    assert res.code == CODE_OK
    assert len(updates) == 1
    vu = updates[0]
    assert vu.pub_key_type == "ed25519"
    assert vu.pub_key == owner.pub_key().bytes()  # envelope key is consensus key
    assert vu.power == 40
    # stake debited from the faucet-opened balance, nonce bumped
    assert app._account(_addr(owner)) == (DEFAULT_FAUCET - 40, 1)
    # bonding more adds power on the same record
    _, updates = _block(app, 2, make_bond_tx(owner, 5, 1))
    assert updates[0].power == 45


def test_staking_bond_overdraft_rejected():
    app = StakingApplication(faucet=30)
    assert (
        app.check_tx(t.RequestCheckTx(tx=make_bond_tx(_key(5), 31, 0))).code
        == CODE_INSUFFICIENT_FUNDS
    )


def test_staking_unbond_partial_and_full():
    app = StakingApplication()
    owner = _key(5)
    _block(app, 1, make_bond_tx(owner, 40, 0))
    (res,), updates = _block(app, 2, make_unbond_tx(owner, 15, 1))
    assert res.code == CODE_OK and updates[0].power == 25
    assert app._account(_addr(owner)) == (DEFAULT_FAUCET - 25, 2)
    # unbonding more than bonded is rejected
    r = app.deliver_tx(t.RequestDeliverTx(tx=make_unbond_tx(owner, 26, 2)))
    assert r.code == CODE_NO_VALIDATOR
    # unbonding the rest leaves the set (power-0 update, record dropped)
    _, updates = _block(app, 3, make_unbond_tx(owner, 25, 2))
    assert updates[0].power == 0
    assert _addr(owner) not in app.validators
    assert app._account(_addr(owner)) == (DEFAULT_FAUCET, 3)  # fully refunded


def test_staking_edit_power_settles_difference():
    app = StakingApplication()
    owner = _key(5)
    _block(app, 1, make_bond_tx(owner, 40, 0))
    _, updates = _block(app, 2, make_edit_power_tx(owner, 25, 1))
    assert updates[0].power == 25
    assert app._account(_addr(owner)) == (DEFAULT_FAUCET - 25, 2)
    # edit to zero = leave with a full refund
    _, updates = _block(app, 3, make_edit_power_tx(owner, 0, 2))
    assert updates[0].power == 0 and _addr(owner) not in app.validators
    assert app._account(_addr(owner)) == (DEFAULT_FAUCET, 3)


def test_staking_verbs_require_bonded_validator():
    app = StakingApplication()
    owner = _key(5)
    for tx in (
        make_unbond_tx(owner, 1, 0),
        make_edit_power_tx(owner, 1, 0),
        make_rotate_key_tx(owner, "ed25519", _key(6).pub_key().bytes(), 0),
    ):
        assert app.deliver_tx(t.RequestDeliverTx(tx=tx)).code == CODE_NO_VALIDATOR


def test_staking_bond_rejects_consensus_key_held_by_other_owner():
    app = StakingApplication()
    a, b = _key(5), _key(6)
    _block(app, 1, make_bond_tx(a, 10, 0))
    # owner a rotates to a foreign ed25519 key == b's envelope key
    _block(app, 2, make_rotate_key_tx(a, "ed25519", b.pub_key().bytes(), 1))
    r = app.deliver_tx(t.RequestDeliverTx(tx=make_bond_tx(b, 10, 0)))
    assert r.code == CODE_KEY_IN_USE


def test_staking_rotate_to_bls_requires_valid_pop():
    pytest.importorskip("tendermint_tpu.crypto.bls.keys")
    from tendermint_tpu.crypto.bls.keys import BlsPrivKey

    app = StakingApplication()
    owner = _key(5)
    _block(app, 1, make_bond_tx(owner, 40, 0))
    bls = BlsPrivKey.from_secret(b"\x07" * 32)
    pub = bls.pub_key().bytes()
    # no PoP
    r = app.deliver_tx(
        t.RequestDeliverTx(tx=make_rotate_key_tx(owner, "bls12381", pub, 1))
    )
    assert r.code == CODE_BAD_POP
    # PoP for a different key
    other_pop = BlsPrivKey.from_secret(b"\x08" * 32).pop()
    r = app.deliver_tx(
        t.RequestDeliverTx(
            tx=make_rotate_key_tx(owner, "bls12381", pub, 1, pop=other_pop)
        )
    )
    assert r.code == CODE_BAD_POP
    # valid PoP: old key exits at power 0, new key enters at full power
    (res,), updates = _block(
        app, 2, make_rotate_key_tx(owner, "bls12381", pub, 1, pop=bls.pop())
    )
    assert res.code == CODE_OK
    by_key = {vu.pub_key: vu for vu in updates}
    assert by_key[owner.pub_key().bytes()].power == 0
    assert by_key[pub].power == 40 and by_key[pub].pub_key_type == "bls12381"
    assert by_key[pub].pop == bls.pop()
    # rotating back to ed25519 needs no PoP and restores the old identity
    _, updates = _block(
        app, 3, make_rotate_key_tx(owner, "ed25519", owner.pub_key().bytes(), 2)
    )
    by_key = {vu.pub_key: vu for vu in updates}
    assert by_key[pub].power == 0
    assert by_key[owner.pub_key().bytes()].power == 40


def test_staking_rotate_rejects_key_in_use_and_bad_lengths():
    app = StakingApplication()
    a, b = _key(5), _key(6)
    _block(app, 1, make_bond_tx(a, 10, 0), make_bond_tx(b, 10, 0))
    r = app.deliver_tx(
        t.RequestDeliverTx(tx=make_rotate_key_tx(a, "ed25519", b.pub_key().bytes(), 1))
    )
    assert r.code == CODE_KEY_IN_USE
    r = app.deliver_tx(
        t.RequestDeliverTx(tx=make_rotate_key_tx(a, "ed25519", b"\x01" * 31, 1))
    )
    assert r.code != CODE_OK
    r = app.deliver_tx(
        t.RequestDeliverTx(tx=make_rotate_key_tx(a, "sr25519", b"\x01" * 32, 1))
    )
    assert r.code != CODE_OK


def test_staking_epoch_barrel_shift_is_deterministic():
    def build():
        app = StakingApplication(epoch_length=4)
        _block(
            app,
            1,
            make_bond_tx(_key(1), 10, 0),
            make_bond_tx(_key(2), 20, 0),
            make_bond_tx(_key(3), 30, 0),
        )
        return app

    a, b = build(), build()
    # non-boundary heights emit nothing
    assert _block(a, 2)[1] == [] and _block(b, 2)[1] == []
    assert _block(a, 3)[1] == [] and _block(b, 3)[1] == []
    ua = _block(a, 4)[1]
    ub = _block(b, 4)[1]
    assert ua == ub and ua  # identical on every replica
    # the multiset of powers is preserved — only the assignment permutes
    assert sorted(r["power"] for r in a.validators.values()) == [10, 20, 30]
    assert [r["power"] for r in a.validators.values()] != [
        r["power"] for r in b.validators.values()
    ] or a.app_hash == b.app_hash
    # another epoch keeps shifting; 3 validators -> period 3
    _block(a, 5)
    _block(a, 6)
    _block(a, 7)
    u8 = _block(a, 8)[1]
    assert u8
    for _ in range(4):
        for h in range(9, 13):
            _block(a, h)
    # app hash stays deterministic through epochs
    assert a.app_hash


def test_staking_epoch_noop_for_single_validator():
    app = StakingApplication(epoch_length=2)
    _block(app, 1, make_bond_tx(_key(1), 10, 0))
    assert _block(app, 2)[1] == []


def test_staking_records_persist_across_restart():
    db = MemDB()
    app = StakingApplication(db=db)
    app.init_chain(
        t.RequestInitChain(
            app_state_bytes=json.dumps({"staking": {"epoch_length": 8}}).encode()
        )
    )
    owner = _key(5)
    _block(app, 1, make_bond_tx(owner, 40, 0))
    app2 = StakingApplication(db=db)
    assert app2.epoch_length == 8  # persisted at init_chain
    rec = app2.validators[_addr(owner)]
    assert rec["power"] == 40 and rec["pub_key"] == owner.pub_key().bytes()
    assert app2.by_pubkey[owner.pub_key().bytes()] == _addr(owner)
    assert app2.app_hash == app.app_hash


def test_staking_query_paths():
    app = StakingApplication()
    owner = _key(5)
    _block(app, 1, make_bond_tx(owner, 40, 0))
    q = app.query(t.RequestQuery(path="validator", data=_addr(owner)))
    assert q.code == t.CODE_TYPE_OK
    rec = json.loads(q.value)
    assert rec["power"] == 40 and rec["key_type"] == "ed25519"
    q = app.query(t.RequestQuery(path="validators"))
    assert _addr(owner).hex() in json.loads(q.value)
    # bank query paths still work through the staking app
    q = app.query(t.RequestQuery(path="nonce", data=_addr(owner)))
    assert int(q.value) == 1
    assert app.query(t.RequestQuery(path="validator", data=b"\x00" * 20)).code != 0


def test_staking_state_digest_covers_validator_records():
    a, b = StakingApplication(), StakingApplication()
    _block(a, 1, make_bond_tx(_key(5), 40, 0))
    _block(b, 1, make_bond_tx(_key(5), 41, 0))
    assert a.app_hash != b.app_hash


def test_staking_bank_transfers_still_flow():
    app = StakingApplication()
    a, b = _key(1), _key(2)
    (r0, r1), updates = _block(
        app, 1, make_transfer_tx(a, _addr(b), 10, 0), make_bond_tx(a, 5, 1)
    )
    assert r0.code == CODE_OK and r1.code == CODE_OK
    assert len(updates) == 1 and updates[0].power == 5
    assert app._account(_addr(a)) == (DEFAULT_FAUCET - 15, 2)
