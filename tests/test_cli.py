"""CLI + testnet-generator tests (reference: cmd/tendermint/commands).

The localnet test is the VERDICT #9 criterion: a 4-node net launches from
CLI-generated config trees (no hand-written Python wiring) and commits
blocks.
"""

import asyncio
import json
import os

from tendermint_tpu.cli import main as cli_main
from tendermint_tpu.config import load_config


def run_cli(*argv):
    return cli_main(list(argv))


class TestBasicCommands:
    def test_init_creates_tree(self, tmp_path, capsys):
        home = str(tmp_path / "home")
        assert run_cli("--home", home, "init", "--chain-id", "cli-chain") == 0
        assert os.path.exists(os.path.join(home, "config", "config.toml"))
        assert os.path.exists(os.path.join(home, "config", "genesis.json"))
        assert os.path.exists(os.path.join(home, "config", "priv_validator_key.json"))
        assert os.path.exists(os.path.join(home, "config", "node_key.json"))
        cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
        assert cfg.base.chain_id == "cli-chain"

    def test_gen_validator_json(self, capsys):
        assert run_cli("gen_validator") == 0
        d = json.loads(capsys.readouterr().out)
        assert len(bytes.fromhex(d["priv_key"]["value"])) == 32

    def test_show_node_id_and_validator(self, tmp_path, capsys):
        home = str(tmp_path / "home")
        run_cli("--home", home, "init")
        capsys.readouterr()
        assert run_cli("--home", home, "show_node_id") == 0
        node_id = capsys.readouterr().out.strip()
        assert len(node_id) == 40  # hex address
        assert run_cli("--home", home, "show_validator") == 0
        d = json.loads(capsys.readouterr().out)
        assert len(bytes.fromhex(d["value"])) == 32

    def test_unsafe_reset_all(self, tmp_path, capsys):
        home = str(tmp_path / "home")
        run_cli("--home", home, "init")
        marker = os.path.join(home, "data", "blockstore.db")
        open(marker, "w").write("x")
        assert run_cli("--home", home, "unsafe_reset_all") == 0
        assert not os.path.exists(marker)

    def test_version(self, capsys):
        assert run_cli("version") == 0
        assert capsys.readouterr().out.strip()


class TestTestnet:
    def test_generates_wired_configs(self, tmp_path, capsys):
        out = str(tmp_path / "net")
        assert run_cli("testnet", "-v", "4", "-o", out, "--chain-id", "tn") == 0
        genesis_hashes = set()
        ids = []
        for i in range(4):
            home = os.path.join(out, f"node{i}")
            cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
            assert cfg.base.chain_id == "tn"
            peers = cfg.p2p.persistent_peers.split(",")
            assert len(peers) == 3  # everyone else
            from tendermint_tpu.types import GenesisDoc

            gen = GenesisDoc.from_file(cfg.genesis_file())
            assert len(gen.validators) == 4
            genesis_hashes.add(gen.validator_hash())
            from tendermint_tpu.p2p.key import NodeKey

            ids.append(NodeKey.load(cfg.node_key_file()).id)
        assert len(genesis_hashes) == 1  # identical genesis everywhere
        assert len(set(ids)) == 4

    def test_bls_key_type_end_to_end(self, tmp_path):
        """Satellite: `testnet --key-type bls12381` end to end — keygen,
        address derivation, key-file round-trip, and a PoP-carrying
        genesis that passes the rogue-key gate."""
        from tendermint_tpu.crypto.bls import BlsPubKey
        from tendermint_tpu.crypto.tmhash import sum_truncated
        from tendermint_tpu.privval.file import FilePV
        from tendermint_tpu.types import GenesisDoc

        out = str(tmp_path / "blsnet")
        assert run_cli("testnet", "-v", "3", "-o", out, "--key-type", "bls12381",
                       "--chain-id", "bls-tn") == 0
        gen = None
        for i in range(3):
            home = os.path.join(out, f"node{i}")
            cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
            assert cfg.base.key_type == "bls12381"
            pv = FilePV.load(
                cfg.priv_validator_key_file(), cfg.priv_validator_state_file()
            )
            pub = pv.get_pub_key()
            assert isinstance(pub, BlsPubKey) and len(pub.bytes()) == 48
            assert pv.address() == sum_truncated(pub.bytes())
            again = FilePV.load(
                cfg.priv_validator_key_file(), cfg.priv_validator_state_file()
            )
            assert again.get_pub_key().bytes() == pub.bytes()
            assert again.address() == pv.address()
            gen = GenesisDoc.from_file(cfg.genesis_file())
            gen.validate_and_complete()  # PoP enforcement must pass on real files
        assert all(
            isinstance(v.pub_key, BlsPubKey) and v.pop for v in gen.validators
        )
        # `init --key-type bls12381` takes the same path for a solo node
        solo = str(tmp_path / "solo")
        assert run_cli("--home", solo, "init", "--chain-id", "bls-solo",
                       "--key-type", "bls12381") == 0
        cfg = load_config(os.path.join(solo, "config", "config.toml"), home=solo)
        assert cfg.base.key_type == "bls12381"
        pv = FilePV.load(
            cfg.priv_validator_key_file(), cfg.priv_validator_state_file()
        )
        assert isinstance(pv.get_pub_key(), BlsPubKey)
        GenesisDoc.from_file(cfg.genesis_file()).validate_and_complete()

    async def test_localnet_from_generated_configs(self, tmp_path):
        """Launch all 4 nodes exactly as `node` would (default_new_node on
        the generated config tree) and watch them commit together."""
        from tendermint_tpu.node import default_new_node

        from tests.test_tools import _free_base_port

        out = str(tmp_path / "net")
        run_cli("testnet", "-v", "4", "-o", out, "--base-port", str(_free_base_port(4)))
        nodes = []
        try:
            for i in range(4):
                home = os.path.join(out, f"node{i}")
                cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
                # operator-style tweaks for CI: memdb speed + quiet engine
                # (the device path is covered by test_node_wiring)
                cfg.base.db_backend = "memdb"
                cfg.tpu.enabled = False
                cfg.rpc.laddr = ""
                cfg.base.fast_sync = False
                cfg.consensus.timeout_commit = 0.1
                cfg.consensus.timeout_propose = 2.0
                nodes.append(default_new_node(cfg))
            await asyncio.gather(*(n.start() for n in nodes))

            async def all_reach(h):
                while not all(n.block_store.height() >= h for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(all_reach(2), 60.0)
            hashes = {n.block_store.load_block(1).hash() for n in nodes}
            assert len(hashes) == 1
        finally:
            for n in nodes:
                if n.is_running:
                    await n.stop()


class TestDebugBundles:
    """`debug dump --offline`: a dead node's forensics bundle built purely
    from its home directory — the spool replay stands in for the live
    recorder, and the derived span report proves the pre-crash chains."""

    def _crashed_home(self, tmp_path, heights=6):
        from tendermint_tpu.libs.tracing import FlightRecorder, FlightSpool

        home = str(tmp_path / "home")
        run_cli("--home", home, "init", "--chain-id", "dbg-chain")
        cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
        rec = FlightRecorder(size=8192)
        sp = FlightSpool(cfg.flight_spool_file(), rec, node="dbg-node")
        for h in range(1, heights + 1):
            for s in ("Propose", "Prevote", "Precommit", "Commit"):
                rec.record("step", height=h, round=0, step=s)
            rec.record("commit", height=h, txs=0, block=f"h{h}")
            sp.flush()
        # NO close(): the node was SIGKILLed — the spool is all there is
        return home

    def test_debug_dump_offline_reconstructs_from_spool(self, tmp_path, capsys):
        import tarfile

        home = self._crashed_home(tmp_path)
        out = str(tmp_path / "bundles")
        assert run_cli(
            "--home", home, "debug", "dump", "--offline", "--output", out
        ) == 0
        capsys.readouterr()
        bundles = [f for f in os.listdir(out) if f.endswith(".tar.gz")]
        assert len(bundles) == 1
        sections = {}
        with tarfile.open(os.path.join(out, bundles[0])) as tar:
            for m in tar.getmembers():
                sections[os.path.basename(m.name)] = tar.extractfile(m).read()
        assert {"manifest.json", "config.toml", "spool.json",
                "span_report.json", "loop_report.json",
                "flight.spool.tail"} <= set(sections)
        manifest = json.loads(sections["manifest.json"])
        assert manifest["mode"] == "offline"
        assert manifest["event_source"] == "spool"
        # the acceptance shape: every interior pre-crash height has a
        # complete propose→prevote→precommit→commit chain, from disk alone
        rep = json.loads(sections["span_report.json"])
        assert rep["bad"] == {} and rep["interior"] == 4
        assert len(rep["complete"]) == rep["interior"]
        spool = json.loads(sections["spool.json"])
        assert spool["node"] == "dbg-node" and spool["events"]
        # offline mode never touched the RPC sections
        assert "status.json" not in sections

    def test_debug_dump_periodic_count(self, tmp_path, capsys):
        home = self._crashed_home(tmp_path, heights=3)
        out = str(tmp_path / "periodic")
        assert run_cli(
            "--home", home, "debug", "dump", "--offline", "--output", out,
            "--frequency", "0.05", "--count", "2",
        ) == 0
        capsys.readouterr()
        assert len([f for f in os.listdir(out) if f.endswith(".tar.gz")]) == 2

    def test_debug_dump_live_degrades_to_home_dir_when_rpc_dead(
        self, tmp_path, capsys
    ):
        import tarfile

        home = self._crashed_home(tmp_path, heights=3)
        out = str(tmp_path / "degraded")
        # no --offline, but nothing listens on the laddr: the bundle must
        # still be written from the home dir, with the RPC failure noted
        assert run_cli(
            "--home", home, "debug", "dump", "--output", out,
            "--rpc-laddr", "127.0.0.1:1",
        ) == 0
        capsys.readouterr()
        bundles = [f for f in os.listdir(out) if f.endswith(".tar.gz")]
        assert len(bundles) == 1
        with tarfile.open(os.path.join(out, bundles[0])) as tar:
            names = {os.path.basename(m.name) for m in tar.getmembers()}
        assert "spool.json" in names and "config.toml" in names
