"""Subprocess entry for the fail-point kill harness
(reference: test/persist/test_failure_indices.sh runs the real binary with
FAIL_TEST_INDEX and asserts recovery).

Runs a solo-validator node from a CLI-initialized home until the block
store reaches --blocks, then exits 0.  With FAIL_TEST_INDEX set, libs/fail
os._exits at that call index instead.
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--home", required=True)
    p.add_argument("--blocks", type=int, default=3)
    p.add_argument("--timeout", type=float, default=60.0)
    args = p.parse_args()

    from tendermint_tpu.config import load_config
    from tendermint_tpu.node import default_new_node

    cfg = load_config(os.path.join(args.home, "config", "config.toml"), home=args.home)
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    cfg.tpu.enabled = False
    cfg.consensus.timeout_commit = 0.02
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_propose = 2.0
    node = default_new_node(cfg)

    async def run() -> int:
        await node.start()
        target = node.block_store.height() + args.blocks

        async def wait():
            while node.block_store.height() < target:
                await asyncio.sleep(0.02)

        try:
            await asyncio.wait_for(wait(), args.timeout)
        finally:
            await node.stop()
        return 0

    return asyncio.run(run())


if __name__ == "__main__":
    sys.exit(main())
