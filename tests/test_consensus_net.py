"""Multi-validator consensus network tests — the workhorse tier
(SURVEY.md §4 tier 2: consensus/reactor_test.go + common_test.go
randConsensusNet over in-memory-connected switches).

Full nodes with real p2p switches on localhost, real gossip reactors, and
the batch-verification vote path.
"""

import asyncio

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.events import EVENT_NEW_BLOCK, query_for_event

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "net-test-chain"


async def make_net(tmp_path, n, name="net"):
    """N-validator network of full nodes meshed over localhost."""
    pvs = sorted([MockPV() for _ in range(n)], key=lambda pv: pv.address())
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=_FAST_IOTA_PARAMS,
    )
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = make_test_cfg(str(tmp_path / f"{name}{i}"))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "memdb"
        cfg.p2p.laddr = "127.0.0.1:0"
        # slower gossip timeouts are fine; commit timeout gives peers time
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.timeout_commit = 0.1
        node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
        nodes.append(node)
    for node in nodes:
        await node.start()
    # full mesh
    for i in range(n):
        for j in range(i + 1, n):
            addr = f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
            await nodes[i].switch.dial_peer(addr)
    for _ in range(300):
        if all(node.switch.num_peers() == n - 1 for node in nodes):
            break
        await asyncio.sleep(0.01)
    return nodes, pvs


async def stop_net(nodes):
    for node in nodes:
        if node.is_running:
            await node.stop()


async def wait_all_height(nodes, h, timeout=30.0):
    async def _wait():
        while True:
            if all(n.block_store.height() >= h for n in nodes):
                return
            await asyncio.sleep(0.05)

    await asyncio.wait_for(_wait(), timeout)


class TestConsensusNet:
    async def test_four_validators_agree(self, tmp_path):
        nodes, pvs = await make_net(tmp_path, 4)
        try:
            await wait_all_height(nodes, 3)
            # all nodes committed identical blocks
            for h in range(1, 4):
                hashes = {n.block_store.load_block(h).hash() for n in nodes}
                assert len(hashes) == 1, f"height {h} diverged"
            # every node's commit for h=2 carries signatures from 4 validators
            commit = nodes[0].block_store.load_block_commit(2)
            assert commit.size() == 4
            present = sum(1 for cs in commit.signatures if not cs.is_absent())
            assert present >= 3  # +2/3 of 4
        finally:
            await stop_net(nodes)

    async def test_tx_gossip_and_commit(self, tmp_path):
        nodes, _ = await make_net(tmp_path, 4)
        try:
            await wait_all_height(nodes, 1)
            # submit on node 3 only; mempool gossip must carry it to the
            # proposer eventually and every app must apply it
            await nodes[3].mempool.check_tx(b"gossip-key=gossip-val")

            async def applied_everywhere():
                from tendermint_tpu.abci.types import RequestQuery

                while True:
                    vals = []
                    for n in nodes:
                        q = await n.proxy_app.query().query(RequestQuery(data=b"gossip-key"))
                        vals.append(q.value)
                    if all(v == b"gossip-val" for v in vals):
                        return
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(applied_everywhere(), 30.0)
        finally:
            await stop_net(nodes)

    async def test_node_catches_up_after_join(self, tmp_path):
        # start 3 of 4 validators; they have +2/3 (30 of 40) and progress.
        # The 4th joins late and must catch up via consensus catchup gossip.
        from tendermint_tpu.privval.file import DoubleSignError

        class _GuardedPV:
            """The restarted validator with its persisted last-sign state:
            a file-backed privval refuses to re-sign heights it signed
            before the restart (FilePV.check_hrs) instead of double-signing
            them — without this, the rejoining MockPV races catchup gossip
            and can sign a conflicting height-1 vote, which correctly
            halts it (state.go: conflicting vote from ourselves)."""

            def __init__(self, inner, floor_height):
                self._inner = inner
                self._floor = floor_height

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def sign_vote(self, chain_id, vote):
                if vote.height <= self._floor:
                    raise DoubleSignError(f"already signed height {vote.height}")
                self._inner.sign_vote(chain_id, vote)

            def sign_proposal(self, chain_id, proposal):
                if proposal.height <= self._floor:
                    raise DoubleSignError(f"already signed height {proposal.height}")
                self._inner.sign_proposal(chain_id, proposal)

        nodes, pvs = await make_net(tmp_path, 4)
        try:
            late = nodes[3]
            await late.stop()
            signed_floor = late.block_store.height() + 1  # +1: in-flight round
            rest = nodes[:3]
            await wait_all_height(rest, 3)

            cfg = make_test_cfg(str(tmp_path / "late-rejoin"))
            cfg.rpc.laddr = ""
            cfg.base.db_backend = "memdb"
            cfg.p2p.laddr = "127.0.0.1:0"
            cfg.consensus.skip_timeout_commit = False
            cfg.consensus.timeout_commit = 0.1
            gen = GenesisDoc(
                chain_id=CHAIN_ID,
                genesis_time_ns=1_700_000_000_000_000_000,
                validators=[
                    GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs
                ],
                consensus_params=_FAST_IOTA_PARAMS,
            )
            rejoin = Node(
                cfg, gen, priv_validator=_GuardedPV(pvs[3], signed_floor), db_backend="memdb"
            )
            await rejoin.start()
            for peer_node in rest:
                addr = f"{peer_node.node_key.id}@{peer_node.switch.transport.listen_addr}"
                await rejoin.switch.dial_peer(addr)
            target = rest[0].block_store.height() + 2
            await wait_all_height(rest + [rejoin], target, timeout=60.0)
            # the rejoined node holds the same blocks
            h = min(target, rejoin.block_store.height())
            assert rejoin.block_store.load_block(h).hash() == rest[0].block_store.load_block(h).hash()
            await rejoin.stop()
        finally:
            await stop_net(nodes)


class TestByzantineResilience:
    async def test_unwanted_round_vote_storm_does_not_halt(self, tmp_path):
        """A peer spraying validly-signed votes across 3+ future rounds used
        to raise GotVoteFromUnwantedRoundError out of the receive loop and
        permanently halt the node (round-1 advisor high finding).  The storm
        must be treated as peer misbehaviour; the net keeps committing."""
        import time as _time

        from tendermint_tpu.types import BlockID, Vote
        from tendermint_tpu.types.canonical import PREVOTE_TYPE

        nodes, pvs = await make_net(tmp_path, 4, name="storm")
        try:
            await wait_all_height(nodes, 2)
            target = nodes[1]
            attacker = pvs[0]
            h = target.consensus.rs.height
            # rounds 3 and 4 consume the two allowed catchup rounds for this
            # peer; round 5 raises GotVoteFromUnwantedRoundError inside the
            # serialized receive loop
            for r in (3, 4, 5):
                v = Vote(
                    type=PREVOTE_TYPE,
                    height=h,
                    round=r,
                    block_id=BlockID(),
                    timestamp_ns=_time.time_ns(),
                    validator_address=attacker.address(),
                    validator_index=0,
                )
                attacker.sign_vote(CHAIN_ID, v)
                await target.consensus.add_vote_input(v, peer_id="evil-peer")
            before = target.block_store.height()
            await wait_all_height(nodes, before + 2)
            assert target.consensus.is_running
        finally:
            await stop_net(nodes)


class TestByzantineEvidence:
    async def test_double_sign_evidence_committed(self, tmp_path):
        """A validator double-signs; the conflict is detected, evidence
        enters the pool, gossips, and lands in a committed block
        (byzantine_test.go + evidence reactor flow)."""
        import time as _time

        from tendermint_tpu.types import BlockID, PartSetHeader, Vote
        from tendermint_tpu.types.canonical import PREVOTE_TYPE

        nodes, pvs = await make_net(tmp_path, 4, name="byz")
        try:
            await wait_all_height(nodes, 2)
            byz = pvs[0]
            target = nodes[1]
            h = target.consensus.rs.height
            # two conflicting prevotes for a catchup round of the current height
            votes = []
            for seed in (b"\x0a", b"\x0b"):
                v = Vote(
                    type=PREVOTE_TYPE,
                    height=h,
                    round=5,
                    block_id=BlockID(seed * 32, PartSetHeader(1, seed * 32)),
                    timestamp_ns=_time.time_ns(),
                    validator_address=byz.address(),
                    validator_index=0,
                )
                byz.sign_vote(CHAIN_ID, v)
                votes.append(v)
            await target.consensus.add_vote_input(votes[0], peer_id="byz-peer")
            await target.consensus.add_vote_input(votes[1], peer_id="byz-peer")

            async def evidence_committed():
                while True:
                    for n in nodes:
                        pend = n.evidence_pool.pending_evidence()
                        for ev in pend + []:
                            if n.evidence_pool.is_committed(ev):
                                return n
                    # also scan recent blocks for included evidence
                    for n in nodes:
                        for hh in range(1, n.block_store.height() + 1):
                            b = n.block_store.load_block(hh)
                            if b is not None and b.evidence:
                                return n
                    await asyncio.sleep(0.05)

            found = await asyncio.wait_for(evidence_committed(), 30.0)
            assert found is not None
        finally:
            await stop_net(nodes)


class TestEvidenceWithholding:
    async def test_evidence_withheld_until_peer_catches_up(self, tmp_path):
        """evidence/reactor.go:157 — evidence for a height the peer hasn't
        reached is withheld, then delivered once the peer catches up."""
        import asyncio as _aio

        from tendermint_tpu.evidence_reactor import EvidenceReactor
        from tendermint_tpu.evidence import EvidencePool
        from tendermint_tpu.libs.kvstore import open_db
        from tendermint_tpu.state.store import StateStore

        sent_batches = []

        class _PS:
            height = 3

        class _Peer:
            id = "peer-ev"

            def get(self, key):
                # the consensus reactor publishes PeerRoundState on the peer
                return _PS() if key == "cs_peer_state" else None

            async def send(self, chan, msg):
                from tendermint_tpu.encoding import codec

                sent_batches.append(codec.loads(msg)["evidence"])
                return True

        from tendermint_tpu.types import BlockID, PartSetHeader, Vote
        from tendermint_tpu.types.canonical import PREVOTE_TYPE
        from tendermint_tpu.types.evidence import DuplicateVoteEvidence

        pv = MockPV()

        def _vote(blk):
            v = Vote(
                type=PREVOTE_TYPE, height=5, round=0,
                block_id=BlockID(blk, PartSetHeader(1, b"\x02" * 32)),
                timestamp_ns=1, validator_address=pv.address(), validator_index=0,
            )
            pv.sign_vote(CHAIN_ID, v)
            return v

        ev = DuplicateVoteEvidence.from_votes(
            pv.get_pub_key(), _vote(b"\x01" * 32), _vote(b"\x03" * 32)
        )
        state_db = open_db("state", None, "memdb")
        pool = EvidencePool(open_db("ev", None, "memdb"), StateStore(state_db))
        pool.pending_evidence = lambda max_num=-1: [ev]

        reactor = EvidenceReactor(pool)

        peer = _Peer()
        await reactor.start()
        try:
            await reactor.add_peer(peer)
            await _aio.sleep(0.3)
            assert sent_batches == []  # withheld: peer at 3 < ev height 5
            _PS.height = 6  # peer caught up
            await _aio.sleep(0.3)  # catchup retry interval is 0.1s
            assert len(sent_batches) == 1 and sent_batches[0][0].hash() == ev.hash()
            await _aio.sleep(0.3)
            assert len(sent_batches) == 1  # not re-sent
        finally:
            await reactor.stop()
