"""RPC layer tests (reference: rpc/core, rpc/lib/server, rpc/client).

A live single-validator node serves HTTP JSON-RPC + WebSocket; clients
exercise the route surface, broadcast_tx_commit round-trips CheckTx →
DeliverTx event, and WS subscriptions stream NewBlock.
"""

import asyncio

import pytest

from tendermint_tpu.config import test_config as make_test_cfg
from tendermint_tpu.node import Node
from tendermint_tpu.rpc import HTTPClient, LocalClient, RPCError, WSClient
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV, SignedHeader

from tendermint_tpu.types.params import BlockParams as _BP, ConsensusParams as _CP

# time_iota_ms=1: test chains commit ~10 blocks/sec (skip_timeout_commit), so the
# reference's default 1000 ms BFT-time step would race header time ahead of wall
# clock and trip clock-drift guards (lite2 + propose-side) under suite load
_FAST_IOTA_PARAMS = _CP(block=_BP(time_iota_ms=1))

CHAIN_ID = "rpc-test-chain"


async def make_rpc_node(tmp_path, name="rpc"):
    pv = MockPV()
    gen = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
        consensus_params=_FAST_IOTA_PARAMS,
    )
    cfg = make_test_cfg(str(tmp_path / name))
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.skip_timeout_commit = False
    cfg.consensus.timeout_commit = 0.05
    node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
    await node.start()
    return node


async def wait_height(node, h, timeout=20.0):
    async def _wait():
        while node.block_store.height() < h:
            await asyncio.sleep(0.02)

    await asyncio.wait_for(_wait(), timeout)


class TestHTTPRoutes:
    async def test_status_block_validators_commit(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 3)
            async with HTTPClient(node.rpc_server.listen_addr) as c:
                st = await c.status()
                assert st["node_info"]["network"] == CHAIN_ID
                assert st["sync_info"]["latest_block_height"] >= 3
                assert not st["sync_info"]["catching_up"]
                assert st["validator_info"]["voting_power"] == 10

                blk = await c.block(2)
                assert blk["block"].header.height == 2
                assert blk["block"].header.chain_id == CHAIN_ID

                # typed SignedHeader round-trips; its commit verifies
                # against the validator set from the same RPC surface
                com = await c.commit(2)
                sh = com["signed_header"]
                assert isinstance(sh, SignedHeader)
                assert com["canonical"] is True
                sh.validate_basic(CHAIN_ID)

                vals = await c.validators(2)
                assert vals["total"] == 1
                assert vals["validators"][0]["voting_power"] == 10

                bc = await c.blockchain(1, 3)
                assert bc["block_metas"][0].header.height == 3

                gen = await c.genesis()
                assert gen["genesis"]["chain_id"] == CHAIN_ID

                # watchdog on by default: /health serves the aggregate
                # verdict now (reference parity `{}` survives only with
                # the watchdog off) — a fresh committing node is `ok`
                hl = await c.health()
                assert hl["verdict"] == "ok" and hl["ok"] is True
                assert hl["alarms"] == {}

                cs = await c.consensus_state()
                assert cs["round_state"]["height"] >= 3

                dump = await c.dump_consensus_state()
                assert "round_state" in dump and "peers" in dump

                ni = await c.net_info()
                assert ni["n_peers"] == 0
        finally:
            await node.stop()

    async def test_broadcast_tx_commit_roundtrip(self, tmp_path):
        """The rpc/core/mempool.go:56 flow: CheckTx → wait for the tx's
        DeliverTx event → result carries both responses + height."""
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 1)
            async with HTTPClient(node.rpc_server.listen_addr) as c:
                res = await c.broadcast_tx_commit(b"rpc-key=rpc-val")
                assert res["check_tx"]["code"] == 0
                assert res["deliver_tx"]["code"] == 0
                assert res["height"] > 0

                # the app applied it
                q = await c.abci_query(data=b"rpc-key")
                assert q["response"]["value"] == b"rpc-val"

                # and the indexer can find it
                got = await c.tx(res["hash"])
                assert got["tx"] == b"rpc-key=rpc-val"
                assert got["height"] == res["height"]

                found = await c.tx_search(f"tx.height={res['height']}")
                assert found["total_count"] >= 1

                proved = await c.tx(res["hash"], prove=True)
                assert "proof" in proved
        finally:
            await node.stop()

    async def test_broadcast_tx_sync_and_unconfirmed(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 1)
            async with HTTPClient(node.rpc_server.listen_addr) as c:
                res = await c.broadcast_tx_sync(b"sync-key=sync-val")
                assert res["code"] == 0
                n = await c.num_unconfirmed_txs()
                assert n["total"] >= 0  # may already be reaped
        finally:
            await node.stop()

    async def test_uri_get_and_errors(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 2)
            import aiohttp

            base = f"http://{node.rpc_server.listen_addr}"
            async with aiohttp.ClientSession() as s:
                # GET URI route with coerced params
                async with s.get(f"{base}/block?height=1") as r:
                    d = await r.json()
                    assert d["result"]["block"]["@t"] == "tm/Block"
                # unknown method
                async with s.get(f"{base}/no_such_route") as r:
                    d = await r.json()
                    assert d["error"]["code"] == -32601
                # unsafe route rejected without rpc.unsafe
                async with s.get(f"{base}/unsafe_flush_mempool") as r:
                    d = await r.json()
                    assert "error" in d
                # batch POST
                reqs = [
                    {"jsonrpc": "2.0", "id": 1, "method": "health", "params": {}},
                    {"jsonrpc": "2.0", "id": 2, "method": "status", "params": {}},
                ]
                async with s.post(base, json=reqs) as r:
                    arr = await r.json()
                    assert len(arr) == 2
                # quoted URI string binds to a bytes param via annotation
                # coercion (reference http_uri_handler.go reflection)
                async with s.get(f'{base}/broadcast_tx_sync?tx="uri=bytes"') as r:
                    d = await r.json()
                    assert d["result"]["code"] == 0
                # numeric-looking string stays bytes for a bytes param
                async with s.get(f'{base}/broadcast_tx_sync?tx="1234"') as r:
                    d = await r.json()
                    assert "result" in d
                # unparseable bool errors rather than silently False
                async with s.get(f'{base}/abci_query?data="k"&prove=yes') as r:
                    d = await r.json()
                    assert d["error"]["code"] == -32602
        finally:
            await node.stop()

    async def test_height_param_validation(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 1)
            async with HTTPClient(node.rpc_server.listen_addr) as c:
                with pytest.raises(RPCError):
                    await c.block(10_000)
        finally:
            await node.stop()


class TestWebSocket:
    async def test_subscribe_new_block_streams(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 1)
            async with WSClient(node.rpc_server.listen_addr) as ws:
                events = await ws.subscribe("tm.event='NewBlock'")
                heights = []
                async for ev in events:
                    assert ev["data"]["type"] == "NewBlock"
                    heights.append(ev["data"]["value"]["block"].header.height)
                    if len(heights) >= 2:
                        break
                # consecutive new blocks
                assert heights[1] == heights[0] + 1
                # normal RPC calls work over the same socket
                st = await ws.status()
                assert st["node_info"]["network"] == CHAIN_ID
                await ws.unsubscribe("tm.event='NewBlock'")
        finally:
            await node.stop()

    async def test_subscribe_tx_event(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 1)
            async with WSClient(node.rpc_server.listen_addr) as ws:
                events = await ws.subscribe("tm.event='Tx'")
                async with HTTPClient(node.rpc_server.listen_addr) as c:
                    res = await c.broadcast_tx_commit(b"ws-key=ws-val")
                ev = await asyncio.wait_for(events.__anext__(), 10.0)
                assert ev["data"]["value"]["tx"] == b"ws-key=ws-val"
                assert ev["data"]["value"]["height"] == res["height"]
        finally:
            await node.stop()


class TestLocalClient:
    async def test_local_mirrors_http(self, tmp_path):
        node = await make_rpc_node(tmp_path)
        try:
            await wait_height(node, 2)
            lc = LocalClient(node)
            st = await lc.status()
            assert st["sync_info"]["latest_block_height"] >= 2
            blk = await lc.block(1)
            assert blk["block"].header.height == 1
            com = await lc.commit(1)
            assert isinstance(com["signed_header"], SignedHeader)
            sub = await lc.subscribe("tm.event='NewBlock'")
            ev = await asyncio.wait_for(sub.__anext__(), 10.0)
            assert ev["data"]["type"] == "NewBlock"
        finally:
            await node.stop()
