"""Differential tests: JAX batched ed25519 vs host C backend and the
pure-Python oracle (crypto/ed25519_math.py).

Coverage model: the reference's crypto tests + golden edge cases
(crypto/ed25519/ed25519_test.go, x/crypto semantics: non-canonical S,
corrupted R, wrong pubkey, truncated sigs).
"""

import numpy as np
import pytest

from tendermint_tpu.crypto import batch as batch_hook
from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.crypto.batch_verifier import (
    AsyncBatchVerifier,
    BatchVerifier,
    PubkeyTable,
    prepare_batch,
)
from tendermint_tpu.crypto.keys import Ed25519PrivKey


@pytest.fixture(scope="module")
def verifier():
    return BatchVerifier()


def make_sigs(n, msg_fn=lambda i: f"message-{i}".encode()):
    keys = [Ed25519PrivKey.from_secret(f"key-{i}".encode()) for i in range(n)]
    pubkeys = [k.pub_key().bytes() for k in keys]
    msgs = [msg_fn(i) for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return pubkeys, msgs, sigs


# ---------------------------------------------------------------------------
# field arithmetic vs python ints
# ---------------------------------------------------------------------------


class TestFieldOps:
    def test_mul_matches_python(self):
        from tendermint_tpu.ops import fe

        rng = np.random.default_rng(0)
        for _ in range(20):
            a = int(rng.integers(0, 2**63)) * int(rng.integers(0, 2**63)) % em.P
            b = int(rng.integers(0, 2**63)) ** 4 % em.P
            got = fe.to_int(fe.canonical(fe.mul(fe.from_int(a), fe.from_int(b))))
            assert got == a * b % em.P

    def test_sub_and_canonical(self):
        from tendermint_tpu.ops import fe

        a, b = 5, em.P - 3
        got = fe.to_int(fe.canonical(fe.sub(fe.from_int(a), fe.from_int(b))))
        assert got == (a - b) % em.P

    def test_invert(self):
        from tendermint_tpu.ops import fe

        for v in (2, 12345678901234567890, em.P - 2):
            inv = fe.to_int(fe.canonical(fe.invert(fe.from_int(v))))
            assert v * inv % em.P == 1

    def test_point_add_matches_oracle(self):
        import jax.numpy as jnp

        from tendermint_tpu.ops import ed25519_kernel as ek
        from tendermint_tpu.ops import fe

        def to_ext(pt):  # batch of 1 lane
            return tuple(jnp.asarray(fe.from_int(c)) for c in pt)

        def from_ext(p):
            return tuple(fe.to_int(fe.canonical(c)) for c in p)

        b2 = em.point_double(em.BASE)
        b3 = em.point_add(b2, em.BASE)
        got = from_ext(ek.point_add(to_ext(b2), to_ext(em.BASE)))
        assert em.to_affine(got[:2] + got[2:]) == em.to_affine(b3)
        got_d = from_ext(ek.point_double(to_ext(em.BASE)))
        assert em.to_affine(got_d[:2] + got_d[2:]) == em.to_affine(b2)

    def test_field_torture_int32_bounds(self):
        """Randomized + adversarial values (all-ones limbs, p-1, 2p-ish)
        exercising the int32 magnitude analysis in ops/fe.py."""
        import jax.numpy as jnp

        from tendermint_tpu.ops import fe

        rng = np.random.default_rng(7)
        specials = [0, 1, 19, em.P - 1, em.P - 19, 2**255 - 20, 2**252 + 27742317777372353535851937790883648493]
        vals = specials + [int(rng.integers(0, 2**63)) ** 4 % em.P for _ in range(9)]

        def lanes(ints):  # [20, n] with one lane per value
            arr = np.zeros((fe.N_LIMBS, len(ints)), np.int32)
            for lane, v in enumerate(ints):
                arr[:, lane] = fe.from_int(v)[:, 0]
            return jnp.asarray(arr)

        def to_ints(arr):
            arr = np.asarray(arr)
            return [fe.to_int(arr, lane) for lane in range(arr.shape[1])]

        a = lanes(vals)
        b = lanes(list(reversed(vals)))
        got_mul = to_ints(fe.canonical(fe.mul(a, b)))
        got_sq = to_ints(fe.canonical(fe.square(a)))
        got_add = to_ints(fe.canonical(fe.add(a, b)))
        got_sub = to_ints(fe.canonical(fe.sub(a, b)))
        rv = list(reversed(vals))
        for i, (x, y) in enumerate(zip(vals, rv)):
            assert got_mul[i] == x * y % em.P
            assert got_sq[i] == x * x % em.P
            assert got_add[i] == (x + y) % em.P
            assert got_sub[i] == (x - y) % em.P


# ---------------------------------------------------------------------------
# end-to-end batch verification
# ---------------------------------------------------------------------------


class TestBatchVerifier:
    def test_valid_batch(self, verifier):
        pubkeys, msgs, sigs = make_sigs(5)
        assert verifier.verify(pubkeys, msgs, sigs) == [True] * 5

    def test_mixed_batch(self, verifier):
        pubkeys, msgs, sigs = make_sigs(8)
        bad = list(sigs)
        bad[2] = bad[2][:32] + bytes(32)  # S=0 -> wrong
        bad[5] = bytes(64)  # garbage
        expected = [True, True, False, True, True, False, True, True]
        assert verifier.verify(pubkeys, msgs, bad) == expected

    def test_wrong_message(self, verifier):
        pubkeys, msgs, sigs = make_sigs(3)
        msgs[1] = b"tampered"
        assert verifier.verify(pubkeys, msgs, sigs) == [True, False, True]

    def test_wrong_pubkey(self, verifier):
        pubkeys, msgs, sigs = make_sigs(3)
        pubkeys[0], pubkeys[2] = pubkeys[2], pubkeys[0]
        assert verifier.verify(pubkeys, msgs, sigs) == [False, True, False]

    def test_noncanonical_s_rejected(self, verifier):
        pubkeys, msgs, sigs = make_sigs(1)
        s = int.from_bytes(sigs[0][32:], "little")
        bumped = (s + em.L).to_bytes(32, "little")
        assert verifier.verify(pubkeys, msgs, [sigs[0][:32] + bumped]) == [False]

    def test_corrupted_r_rejected(self, verifier):
        pubkeys, msgs, sigs = make_sigs(1)
        r = bytearray(sigs[0][:32])
        r[0] ^= 1
        assert verifier.verify(pubkeys, msgs, [bytes(r) + sigs[0][32:]]) == [False]

    def test_truncated_sig_and_bad_pubkey(self, verifier):
        pubkeys, msgs, sigs = make_sigs(2)
        assert verifier.verify(pubkeys, msgs, [sigs[0][:63], sigs[1]]) == [False, True]
        assert verifier.verify([b"\xff" * 32, pubkeys[1]], msgs, sigs) == [False, True]

    def test_differential_vs_oracle_random_corruptions(self, verifier):
        rng = np.random.default_rng(42)
        pubkeys, msgs, sigs = make_sigs(32)
        mutated = []
        for i, sig in enumerate(sigs):
            if rng.random() < 0.5:
                b = bytearray(sig)
                b[rng.integers(0, 64)] ^= 1 << rng.integers(0, 8)
                mutated.append(bytes(b))
            else:
                mutated.append(sig)
        got = verifier.verify(pubkeys, msgs, mutated)
        want = [em.verify(pk, m, s) for pk, m, s in zip(pubkeys, msgs, mutated)]
        assert got == want

    def test_batch_padding_shapes(self, verifier):
        # different batch sizes hit the same bucket; larger sizes re-jit once
        for n in (1, 2, 15, 16, 17):
            pubkeys, msgs, sigs = make_sigs(n)
            assert verifier.verify(pubkeys, msgs, sigs) == [True] * n

    def test_empty_batch(self, verifier):
        assert verifier.verify([], [], []) == []


class TestPallasKernel:
    # Interpret-mode runs dispatch every kernel op individually on the CPU —
    # minutes per ladder pass on a small host, so these differential tests
    # are tier-2 (`-m slow`); the quick gate covers the same math through
    # the portable XLA kernel.

    @pytest.mark.slow
    def test_differential_vs_oracle_interpret(self):
        """The Pallas kernel is the default verify path on TPU backends;
        cover its exact code on CPU via the Pallas interpreter."""
        import numpy as np

        from tendermint_tpu.crypto.batch_verifier import prepare_batch
        from tendermint_tpu.ops.ed25519_pallas import verify_prepared_pallas

        rng = np.random.default_rng(11)
        pubkeys, msgs, sigs = make_sigs(8)
        mutated = []
        for sig in sigs:
            if rng.random() < 0.5:
                b = bytearray(sig)
                b[rng.integers(0, 64)] ^= 1 << rng.integers(0, 8)
                mutated.append(bytes(b))
            else:
                mutated.append(sig)
        neg_a, h, s, ry, rs, valid = prepare_batch(pubkeys, msgs, mutated)
        ok = np.asarray(
            verify_prepared_pallas(neg_a, h, s, ry, rs, tile=8, interpret=True)
        )
        got = list(np.logical_and(ok, valid))
        want = [em.verify(pk, m, sg) for pk, m, sg in zip(pubkeys, msgs, mutated)]
        assert got == want

    @pytest.mark.slow
    def test_multi_tile_grid_interpret(self):
        """tile < batch exercises the BlockSpec index maps with grid > 1 —
        a multi-tile indexing bug must surface off-TPU, not only on real
        hardware."""
        import numpy as np

        from tendermint_tpu.crypto.batch_verifier import prepare_batch
        from tendermint_tpu.ops.ed25519_pallas import verify_prepared_pallas

        pubkeys, msgs, sigs = make_sigs(8)
        bad = bytearray(sigs[5])
        bad[3] ^= 0x40  # corrupt one sig so tiles differ in outcome
        sigs = sigs[:5] + [bytes(bad)] + sigs[6:]
        neg_a, h, s, ry, rs, valid = prepare_batch(pubkeys, msgs, sigs)
        ok = np.asarray(
            verify_prepared_pallas(neg_a, h, s, ry, rs, tile=4, interpret=True)
        )
        got = list(np.logical_and(ok, valid))
        want = [em.verify(pk, m, sg) for pk, m, sg in zip(pubkeys, msgs, sigs)]
        assert got == want
        assert got[5] is np.False_ or got[5] == False  # noqa: E712


class TestPubkeyTable:
    def test_verify_indexed(self, verifier):
        pubkeys, msgs, sigs = make_sigs(6)
        table = PubkeyTable(pubkeys, verifier)
        idxs = [3, 1, 5, 0]
        got = table.verify_indexed(
            idxs, [msgs[i] for i in idxs], [sigs[i] for i in idxs]
        )
        assert got == [True] * 4
        # wrong index -> wrong pubkey -> False
        assert table.verify_indexed([0], [msgs[1]], [sigs[1]]) == [False]
        # out-of-range index
        assert table.verify_indexed([99], [msgs[0]], [sigs[0]]) == [False]

    def test_commit_via_hook(self, verifier):
        # ValidatorSet.verify_commit routed through the installed TPU hook
        import time

        from tendermint_tpu.types import PRECOMMIT_TYPE, ValidatorSet, Validator, MockPV, VoteSet
        from tests.test_types import CHAIN_ID, make_block_id, rand_validator_set, signed_vote

        vset, pvs = rand_validator_set(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 5, 0, PRECOMMIT_TYPE, vset)
        for pv in pvs:
            vs.add_vote(signed_vote(pv, vset, PRECOMMIT_TYPE, 5, 0, bid))
        commit = vs.make_commit()
        try:
            verifier.install()
            vset.verify_commit(CHAIN_ID, bid, 5, commit)
        finally:
            batch_hook.set_verifier(None)


class TestTabulated:
    """ops/ed25519_table.py: per-validator window tables, zero-doubling
    verification — differential against the same signatures the ladder
    kernels verify (pallas interpret mode on CPU)."""

    @pytest.mark.slow  # interpret-mode table verify: minutes on a small host
    def test_tabulated_differential(self, verifier):
        pubkeys, msgs, sigs = make_sigs(5)
        table = PubkeyTable(pubkeys, verifier, tabulated=True)
        table._interpret = True
        idxs = [0, 3, 1, 4, 2, 0]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        # corrupt one signature, point one index at the wrong key
        ss[2] = ss[2][:5] + bytes([ss[2][5] ^ 1]) + ss[2][6:]
        idxs[4] = 1
        got = table.verify_indexed(idxs, ms, ss)
        assert got == [True, True, False, True, False, True]

    def test_table_cache_routes_verify_commit(self, verifier):
        """verify_commit uses the installed indexed hook (device-resident
        pubkey rows) and falls back cleanly when the cache declines."""
        from tendermint_tpu.crypto.batch_verifier import TableCache
        from tendermint_tpu.types import PRECOMMIT_TYPE, VoteSet
        from tests.test_types import CHAIN_ID, make_block_id, rand_validator_set, signed_vote

        vset, pvs = rand_validator_set(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 5, 0, PRECOMMIT_TYPE, vset)
        for pv in pvs:
            vs.add_vote(signed_vote(pv, vset, PRECOMMIT_TYPE, 5, 0, bid))
        commit = vs.make_commit()
        cache = TableCache(verifier, tabulated=False)
        calls = {"n": 0}
        orig = cache.verify_indexed

        def counting(*a):
            calls["n"] += 1
            return orig(*a)

        cache.verify_indexed = counting
        try:
            batch_hook.set_indexed_verifier(cache.verify_indexed)
            vset.verify_commit(CHAIN_ID, bid, 5, commit)
            assert calls["n"] == 1
            assert vset.pubkeys_digest() in cache._tables
            # second commit at the same set reuses the cached table
            vset.verify_commit(CHAIN_ID, bid, 5, commit)
            assert len(cache._tables) == 1
        finally:
            batch_hook.set_indexed_verifier(None)

    def test_bad_sig_still_raises_through_indexed_path(self, verifier):
        from tendermint_tpu.crypto.batch_verifier import TableCache
        from tendermint_tpu.types import PRECOMMIT_TYPE, VoteSet
        from tests.test_types import CHAIN_ID, make_block_id, rand_validator_set, signed_vote

        vset, pvs = rand_validator_set(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 5, 0, PRECOMMIT_TYPE, vset)
        for pv in pvs:
            vs.add_vote(signed_vote(pv, vset, PRECOMMIT_TYPE, 5, 0, bid))
        commit = vs.make_commit()
        import dataclasses

        commit.signatures[0] = dataclasses.replace(commit.signatures[0], signature=bytes(64))
        cache = TableCache(verifier, tabulated=False)
        try:
            batch_hook.set_indexed_verifier(cache.verify_indexed)
            with pytest.raises(ValueError, match="wrong signature"):
                vset.verify_commit(CHAIN_ID, bid, 5, commit)
        finally:
            batch_hook.set_indexed_verifier(None)


class TestAsyncBatchVerifier:
    async def test_futures_resolve(self):
        pubkeys, msgs, sigs = make_sigs(4)
        svc = AsyncBatchVerifier(BatchVerifier(), flush_interval=0.01)
        await svc.start()
        try:
            futs = [svc.verify_one(pk, m, s) for pk, m, s in zip(pubkeys, msgs, sigs)]
            bad = svc.verify_one(pubkeys[0], b"other", sigs[0])
            import asyncio

            results = await asyncio.gather(*futs, bad)
            assert results == [True, True, True, True, False]
        finally:
            await svc.stop()


class TestChunkedIndexed:
    def test_double_buffered_chunks_match(self, verifier, monkeypatch):
        """Large indexed batches split into pipelined chunks; results must
        be identical to the one-shot path, incl. padding + invalid rows."""
        from tendermint_tpu.crypto import batch_verifier as bv

        monkeypatch.setattr(bv, "_CHUNK", 32)
        pubkeys, msgs, sigs = make_sigs(12)
        chunk_verifier = BatchVerifier()
        chunk_verifier._pallas = False  # XLA kernel: any chunk shape allowed
        table = PubkeyTable(pubkeys, chunk_verifier)
        table.chunked_single_shot = True
        n = 70
        idxs = [i % 12 for i in range(n)]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        ss[40] = ss[40][:3] + bytes([ss[40][3] ^ 1]) + ss[40][4:]  # corrupt
        idxs[65] = 999  # out-of-range row
        expect = [True] * n
        expect[40] = False
        expect[65] = False
        assert table.verify_indexed(idxs, ms, ss) == expect


class TestWarmup:
    def test_cold_bucket_serves_host_path_then_device(self, verifier):
        """With warmup mode on, an uncompiled bucket shape must answer
        correctly (host path) immediately, and flip to the device path once
        the background compile lands — a cold node never stalls consensus."""
        import time

        pubkeys, msgs, sigs = make_sigs(3)
        bv = BatchVerifier()
        bv._warmup_mode = True  # no pre-compile: every bucket starts cold
        assert bv.verify(pubkeys, msgs, sigs) == [True, True, True]
        # a wrong signature is caught on the fallback path too
        assert bv.verify([pubkeys[0]], [b"other"], [sigs[0]]) == [False]
        deadline = time.time() + 60
        while time.time() < deadline:
            if bv._bucket(3) in bv._ready_buckets:
                break
            time.sleep(0.1)
        assert bv._bucket(3) in bv._ready_buckets
        assert bv.verify(pubkeys, msgs, sigs) == [True, True, True]

    async def test_overflow_falls_back_inline(self):
        pubkeys, msgs, sigs = make_sigs(2)
        svc = AsyncBatchVerifier(BatchVerifier(), flush_interval=0.01, max_pending=1)
        await svc.start()
        try:
            f1 = svc.verify_one(pubkeys[0], msgs[0], sigs[0])
            f2 = svc.verify_one(pubkeys[1], msgs[1], sigs[1])  # over cap: inline host
            assert f2.done() and f2.result() is True
            import asyncio

            assert await asyncio.wait_for(f1, 30) is True
        finally:
            await svc.stop()


def _mesh8():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 virtual CPU devices (conftest XLA_FLAGS)")
    return Mesh(np.array(devs[:8]), ("batch",))


class TestSharded:
    def test_mesh_sharded_verify(self):
        v = BatchVerifier(mesh=_mesh8())
        pubkeys, msgs, sigs = make_sigs(10)
        sigs[7] = bytes(64)
        want = [True] * 10
        want[7] = False
        assert v.verify(pubkeys, msgs, sigs) == want

    def test_sharded_indexed_differential_vs_single_device(self):
        """The sharded fused dispatch must be BIT-IDENTICAL to the
        single-device engine on a mixed valid/invalid indexed batch."""
        pubkeys, msgs, sigs = make_sigs(16)
        n = 96
        idxs = [i % 16 for i in range(n)]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        ss[5] = bytes(64)  # garbage
        ss[33] = ss[33][:10] + bytes([ss[33][10] ^ 0x40]) + ss[33][11:]
        ms[70] = b"forged"  # wrong message
        idxs[90] = 999  # out-of-range validator row

        mesh_tab = PubkeyTable(pubkeys, BatchVerifier(mesh=_mesh8()))
        solo_tab = PubkeyTable(pubkeys, BatchVerifier())
        got_mesh = mesh_tab.verify_indexed(idxs, ms, ss)
        got_solo = solo_tab.verify_indexed(idxs, ms, ss)
        assert got_mesh == got_solo
        expect = [True] * n
        for j in (5, 33, 70, 90):
            expect[j] = False
        assert got_mesh == expect

    def test_liar_attribution_on_every_shard(self):
        """One invalid signature placed at each shard's slice of the batch:
        the verdict vector must point at exactly those rows — a liar on
        shard k must never be blamed on a row owned by shard j."""
        pubkeys, msgs, sigs = make_sigs(16)
        n = 64  # 8 rows per shard on the 8-device mesh
        idxs = [i % 16 for i in range(n)]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        liars = [shard * 8 + 3 for shard in range(8)]  # one per shard
        for j in liars:
            ss[j] = bytes(64)
        expect = [i not in liars for i in range(n)]
        tab = PubkeyTable(pubkeys, BatchVerifier(mesh=_mesh8()))
        assert tab.verify_indexed(idxs, ms, ss) == expect

    def test_ragged_batches_no_verdict_leakage(self):
        """Sizes not divisible by the shard count pad up to the bucket;
        padding rows must never leak into (or flip) real verdicts."""
        pubkeys, msgs, sigs = make_sigs(16)
        tab = PubkeyTable(pubkeys, BatchVerifier(mesh=_mesh8()))
        for n in (13, 27, 67):
            idxs = [i % 16 for i in range(n)]
            ms = [msgs[i] for i in idxs]
            ss = [sigs[i] for i in idxs]
            expect = [True] * n
            ss[n - 1] = bytes(64)
            expect[n - 1] = False
            assert tab.verify_indexed(idxs, ms, ss) == expect, n

    def test_sharded_chunked_matches(self, monkeypatch):
        from tendermint_tpu.crypto import batch_verifier as bv_mod

        monkeypatch.setattr(bv_mod, "_CHUNK", 16)
        pubkeys, msgs, sigs = make_sigs(16)
        tab = PubkeyTable(pubkeys, BatchVerifier(mesh=_mesh8()))
        tab.chunked_single_shot = True
        n = 48
        idxs = [i % 16 for i in range(n)]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        ss[20] = bytes(64)
        expect = [True] * n
        expect[20] = False
        assert tab.verify_indexed(idxs, ms, ss) == expect

    def test_pack_expand_round_trip(self):
        """Host-side packed 32-byte scalars must expand on-device to the
        exact window digits the unpacked wire format would have carried."""
        import jax.numpy as jnp

        from tendermint_tpu.crypto.batch_verifier import _pack_digits, _scalar_rows
        from tendermint_tpu.ops import ed25519_kernel

        pubkeys, msgs, sigs = make_sigs(5)
        items = list(zip(pubkeys, msgs, sigs))
        h_digits, s_digits, _, _, _ = _scalar_rows(items)
        for digits in (h_digits, s_digits):
            packed = _pack_digits(digits)
            assert packed.shape == (len(items), 32)
            expanded = np.asarray(ed25519_kernel.expand_digits(jnp.asarray(packed)))
            np.testing.assert_array_equal(expanded, digits)


class TestResolveMesh:
    def test_off_never_shards(self):
        from tendermint_tpu.crypto.backend import resolve_mesh

        mesh, shards, reason = resolve_mesh("off", 8)
        assert mesh is None and shards == 1 and "off" in reason

    def test_auto_ignores_virtual_cpu_devices(self):
        from tendermint_tpu.crypto.backend import resolve_mesh

        mesh, shards, reason = resolve_mesh("auto", 0)
        assert mesh is None and shards == 1
        assert "virtual cpu" in reason

    def test_auto_with_explicit_device_cap_opts_in(self):
        from tendermint_tpu.crypto.backend import resolve_mesh

        mesh, shards, reason = resolve_mesh("auto", 4)
        assert mesh is not None and shards == 4

    def test_on_shards_any_platform(self):
        from tendermint_tpu.crypto.backend import resolve_mesh

        mesh, shards, reason = resolve_mesh("on", 8)
        assert mesh is not None and shards == 8
        assert "sharded over 8" in reason

    def test_probe_failure_degrades_to_single_device(self, monkeypatch):
        import jax

        from tendermint_tpu.crypto.backend import resolve_mesh

        def boom(*a, **k):
            raise RuntimeError("device plane down")

        monkeypatch.setattr(jax, "devices", boom)
        mesh, shards, reason = resolve_mesh("on", 8)
        assert mesh is None and shards == 1
        assert "mesh probe failed" in reason


class TestShardedWarmup:
    def test_no_compile_after_warmup_on_mesh(self):
        """start_warmup on a mesh engine must compile the SHARDED bucket
        executable — the first live dispatch after warmup lands must not
        trigger any new XLA compilation."""
        import time

        v = BatchVerifier(mesh=_mesh8())
        v.start_warmup()
        b = v._bucket(max(1, v.min_device_batch))
        deadline = time.time() + 120
        while time.time() < deadline and b not in v._ready_buckets:
            time.sleep(0.05)
        assert b in v._ready_buckets, "warmup compile never landed"
        fn = v._jitted()
        compiled = fn._cache_size()
        assert compiled >= 1
        pubkeys, msgs, sigs = make_sigs(3)
        assert v.verify(pubkeys, msgs, sigs) == [True, True, True]
        assert fn._cache_size() == compiled, "post-warmup dispatch recompiled"


class TestMeshConfigKnobs:
    def _cfg(self):
        from tendermint_tpu.config import Config

        return Config(home="/tmp/x")

    @pytest.mark.parametrize("field,bad,match", [
        ("mesh", "sideways", "mesh"),
        ("mesh_devices", -1, "mesh_devices"),
        ("chunk_size", -8, "chunk_size"),
        ("chunk_depth", 0, "chunk_depth"),
        ("tabulated", "maybe", "tabulated"),
    ])
    def test_bad_knob_rejected(self, field, bad, match):
        cfg = self._cfg()
        setattr(cfg.tpu, field, bad)
        with pytest.raises(ValueError, match=match):
            cfg.validate_basic()

    def test_defaults_validate(self):
        cfg = self._cfg()
        cfg.validate_basic()
        assert cfg.tpu.mesh == "auto"
        assert cfg.tpu.chunk_depth == 2
        assert cfg.tpu.tabulated == "auto"


# ---------------------------------------------------------------------------
# fused one-pass C host prep (csrc ed25519_prep_batch)
# ---------------------------------------------------------------------------


class TestFusedHostPrep:
    """The fused C pass must be bit-identical to the numpy reference
    pipeline it replaces — same digits, limbs, sign bits and prefilter
    verdicts for every entry shape callers can produce."""

    def _mixed_items(self):
        pubkeys, msgs, sigs = make_sigs(9, msg_fn=lambda i: b"m" * (i * 37))
        items = [
            (pubkeys[0], msgs[0], sigs[0]),
            None,  # caller-marked invalid
            (pubkeys[2], msgs[2], sigs[2]),
            (pubkeys[3], msgs[3], sigs[3][:40]),  # truncated sig
            (pubkeys[4][:16], msgs[4], sigs[4]),  # bad pubkey length
            # non-canonical S (== L): prefilter must reject
            (pubkeys[5], msgs[5], sigs[5][:32] + em.L.to_bytes(32, "little")),
            (pubkeys[6], b"", sigs[6]),  # empty message (still hashed)
            (pubkeys[7], msgs[7] * 100, sigs[7]),  # multi-block SHA-512 input
            (pubkeys[8], msgs[8], sigs[8]),
        ]
        return items

    def test_differential_vs_numpy_pipeline(self, monkeypatch):
        from tendermint_tpu.crypto import batch_verifier as bv
        from tendermint_tpu.crypto import hostprep

        items = self._mixed_items()
        fused = hostprep.prep_scalar_rows(items)
        if fused is None:
            pytest.skip("no C toolchain: fused prep unavailable")
        monkeypatch.setattr(hostprep, "prep_scalar_rows", lambda _: None)
        reference = bv._scalar_rows(items)
        for got, want, name in zip(
            fused, reference, ("h_digits", "s_digits", "r_y", "r_sign", "valid")
        ):
            np.testing.assert_array_equal(got, want, err_msg=name)

    def test_fused_feeds_verifier_correctly(self, verifier):
        pubkeys, msgs, sigs = make_sigs(24)
        bad = list(sigs)
        bad[7] = bad[7][:10] + bytes([bad[7][10] ^ 0xFF]) + bad[7][11:]
        expect = [True] * 24
        expect[7] = False
        assert verifier.verify(pubkeys, msgs, bad) == expect

    def test_host_verify_batch_matches_serial(self):
        from tendermint_tpu.crypto import hostprep

        pubkeys, msgs, sigs = make_sigs(6)
        sigs = list(sigs)
        sigs[2] = bytes(64)  # garbage
        sigs[4] = sigs[4][:32] + em.L.to_bytes(32, "little")  # non-canonical S
        res = hostprep.host_verify_batch(pubkeys, msgs, sigs)
        if res is None:
            pytest.skip("no C toolchain")
        from tendermint_tpu.crypto.keys import Ed25519PubKey

        want = [Ed25519PubKey(pk).verify(m, s) for pk, m, s in zip(pubkeys, msgs, sigs)]
        assert res == want == [True, True, False, True, False, True]


# ---------------------------------------------------------------------------
# dispatch RTT probe + chunked auto-selection
# ---------------------------------------------------------------------------


class TestRTTProbe:
    def test_probe_shape_and_caching(self):
        bv_inst = BatchVerifier()
        probe = bv_inst.probe_dispatch_rtt(samples=2)
        assert set(probe) == {"dispatch_rtt_ms", "prep_ms_per_chunk", "chunked_selected"}
        assert probe["dispatch_rtt_ms"] > 0
        assert probe["prep_ms_per_chunk"] > 0
        assert bv_inst.probe_dispatch_rtt() is probe  # cached
        assert isinstance(bv_inst.chunked_auto(), bool)

    def test_auto_selection_drives_indexed_path(self, monkeypatch):
        """chunked_single_shot=None defers to the probe verdict; both
        verdicts must produce identical results on the same batch."""
        from tendermint_tpu.crypto import batch_verifier as bv

        monkeypatch.setattr(bv, "_CHUNK", 16)
        pubkeys, msgs, sigs = make_sigs(8)
        n = 40
        idxs = [i % 8 for i in range(n)]
        ms = [msgs[i] for i in idxs]
        ss = [sigs[i] for i in idxs]
        ss[11] = bytes(64)
        expect = [True] * n
        expect[11] = False
        for selected in (0.0, 1.0):
            v = BatchVerifier()
            v._pallas = False
            v.rtt_probe = {
                "dispatch_rtt_ms": 1.0,
                "prep_ms_per_chunk": 2.0,
                "chunked_selected": selected,
            }
            table = PubkeyTable(pubkeys, v)
            assert table.chunked_single_shot is None  # auto by default
            assert table.verify_indexed(idxs, ms, ss) == expect


# ---------------------------------------------------------------------------
# adaptive flush quantum
# ---------------------------------------------------------------------------


class TestAdaptiveFlush:
    def test_quiet_window_policy(self):
        svc = AsyncBatchVerifier(
            BatchVerifier(), flush_interval=0.002, flush_min=0.0002
        )
        # no history: floor (flush as soon as the first window is quiet)
        assert svc._quiet_window() == svc.flush_min
        # sparse regime (next vote far beyond the deadline): floor
        svc._ewma_gap = 0.1
        assert svc._quiet_window() == svc.flush_min
        # trickle regime (more votes imminent): wait ~4 gaps for them
        svc._ewma_gap = 0.0003
        assert svc._quiet_window() == pytest.approx(0.0012)
        # storm regime: gaps tiny, floor again (arrivals re-extend anyway)
        svc._ewma_gap = 0.00001
        assert svc._quiet_window() == svc.flush_min

    async def test_sparse_and_burst_resolve(self):
        import asyncio
        import time

        pubkeys, msgs, sigs = make_sigs(32)
        # 500 ms cap: the fixed-quantum behavior would park a lone vote for
        # the whole cap; adaptive must flush it in ~a quiet window.  The
        # half-cap bound stays robust against CI contention (background
        # warmup compiles share this box's cores).
        svc = AsyncBatchVerifier(BatchVerifier(), flush_interval=0.5)
        await svc.start()
        try:
            assert await svc.verify_one(pubkeys[0], msgs[0], sigs[0]) is True  # warm
            t0 = time.perf_counter()
            assert await svc.verify_one(pubkeys[0], msgs[0], sigs[0]) is True
            assert time.perf_counter() - t0 < 0.25
            # burst: everything lands in one coalesced batch, all correct
            futs = [
                svc.verify_one(pk, m, s)
                for pk, m, s in zip(pubkeys, msgs, sigs)
            ]
            bad = svc.verify_one(pubkeys[0], msgs[1], sigs[0])
            assert await asyncio.gather(*futs) == [True] * 32
            assert await bad is False
        finally:
            await svc.stop()

    async def test_fixed_interval_mode_still_works(self):
        pubkeys, msgs, sigs = make_sigs(3)
        svc = AsyncBatchVerifier(BatchVerifier(), flush_interval=0.002, adaptive=False)
        await svc.start()
        try:
            import asyncio

            futs = [svc.verify_one(pk, m, s) for pk, m, s in zip(pubkeys, msgs, sigs)]
            assert await asyncio.gather(*futs) == [True, True, True]
        finally:
            await svc.stop()
