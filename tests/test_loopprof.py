"""Asyncio scheduler profiler (libs/loopprof.py): category rules, the
resume-timing trampoline (values, exceptions and cancellation must pass
through unchanged), process-hook ownership, GC accounting, the lag
histogram, per-block attribution math, and the overhead contract the
enabled path must honor (the recorder's own per-event tripwire)."""

import asyncio
import gc
import time

import pytest

from tendermint_tpu.libs import loopprof
from tendermint_tpu.libs.loopprof import LoopProfiler
from tendermint_tpu.libs.service import Service
from tendermint_tpu.libs.tracing import FlightRecorder


class TestCategorize:
    def test_spawn_sites_map_to_their_subsystem(self):
        assert loopprof.categorize("ConsensusState", "recv-routine") == "consensus"
        assert loopprof.categorize("ConsensusReactor", "gossip-data-ab12") == "gossip"
        assert loopprof.categorize("ConsensusReactor", "maj23-queries") == "gossip"
        assert loopprof.categorize("batch-verifier", "flush-loop") == "verify"
        assert loopprof.categorize("MConnection", "send-routine") == "p2p-conn"
        assert loopprof.categorize("Switch", "accept-routine") == "p2p-conn"
        assert loopprof.categorize("MempoolReactor", "broadcast") == "mempool"
        assert loopprof.categorize("RPCServer") == "rpc"
        assert loopprof.categorize("SomethingElse") == "other"

    def test_every_rule_lands_in_a_known_category(self):
        for _, cat in loopprof._RULES:
            assert cat in loopprof.CATEGORIES


class _Yield:
    """Awaitable that yields once to whatever drives the coroutine —
    lets tests step the trampoline by hand, no event loop involved."""

    def __await__(self):
        yield None


def _drive_to_completion(coro):
    steps = 0
    try:
        while True:
            coro.send(None)
            steps += 1
    except StopIteration as stop:
        return stop.value, steps


class TestTrampoline:
    def test_return_value_passes_through(self):
        prof = LoopProfiler()

        async def work():
            await _Yield()
            await _Yield()
            return 42

        value, steps = _drive_to_completion(prof.wrap(work(), "consensus"))
        assert value == 42
        assert steps == 2
        # every resume (2 yields + the final run to StopIteration) accounted
        assert prof.steps["consensus"] == 3
        assert prof.busy_ns["consensus"] > 0

    def test_exception_passes_through_and_is_accounted(self):
        prof = LoopProfiler()

        async def boom():
            await _Yield()
            raise ValueError("boom")

        coro = prof.wrap(boom(), "verify")
        coro.send(None)
        with pytest.raises(ValueError, match="boom"):
            coro.send(None)
        assert prof.steps["verify"] == 2

    async def test_cancellation_reaches_the_inner_coroutine(self):
        prof = LoopProfiler()
        cleaned = asyncio.Event()

        async def forever():
            try:
                await asyncio.sleep(3600)
            except asyncio.CancelledError:
                cleaned.set()
                raise

        task = asyncio.get_event_loop().create_task(prof.wrap(forever(), "gossip"))
        await asyncio.sleep(0.01)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert cleaned.is_set(), "CancelledError never reached the wrapped coroutine"

    async def test_values_sent_by_the_loop_pass_through(self):
        # futures resolve THROUGH the trampoline: the loop sends the
        # result back and the inner coroutine must receive it
        prof = LoopProfiler()
        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        async def waiter():
            return await fut

        task = loop.create_task(prof.wrap(waiter(), "other"))
        await asyncio.sleep(0.01)
        fut.set_result("payload")
        assert await task == "payload"

    def test_wrap_overhead_per_resume_budget(self):
        # contract: ~1 us per task resume; tripwire at 5 us (the
        # recorder's own per-event budget) so CI noise can't flake while
        # a 10x regression still fails
        prof = LoopProfiler()
        n = 20_000

        async def hot():
            for _ in range(n):
                await _Yield()

        t0 = time.perf_counter()
        _drive_to_completion(prof.wrap(hot(), "consensus"))
        per_step = (time.perf_counter() - t0) / n
        assert per_step < 5e-6, f"trampoline resume took {per_step * 1e6:.2f} us"


class TestLifecycleAndSpawn:
    async def test_first_profiler_owns_process_hooks(self):
        assert loopprof.active() is None, "a previous test leaked the spawn hook"
        a = LoopProfiler(interval=0.05)
        b = LoopProfiler(interval=0.05)
        await a.start()
        await b.start()
        try:
            assert loopprof.active() is a
            assert a._owns_hooks and not b._owns_hooks
        finally:
            await b.stop()
            assert loopprof.active() is a  # non-owner stop doesn't release
            await a.stop()
        assert loopprof.active() is None

    async def test_spawn_accounts_to_category_when_active(self):
        prof = LoopProfiler(interval=0.05)
        await prof.start()
        svc = Service("MempoolReactor")
        done = asyncio.Event()

        async def job():
            await asyncio.sleep(0)
            done.set()

        try:
            svc.spawn(job(), "broadcast")
            await asyncio.wait_for(done.wait(), 5)
            await asyncio.sleep(0)  # let the trampoline run to StopIteration
            assert prof.busy_ns["mempool"] > 0
            assert prof.steps["mempool"] >= 1
        finally:
            await svc.stop()
            await prof.stop()

    async def test_spawn_untouched_without_profiler(self):
        assert loopprof.active() is None
        svc = Service("ConsensusState")
        done = asyncio.Event()

        async def job():
            done.set()

        try:
            svc.spawn(job(), "recv-routine")
            await asyncio.wait_for(done.wait(), 5)
        finally:
            await svc.stop()


class TestProbe:
    async def test_probe_emits_lag_busy_queue_and_gc_events(self):
        rec = FlightRecorder(size=512)
        prof = LoopProfiler(interval=0.02, recorder=rec)
        prof.add_queue_probe("stub_queue", lambda: 7)
        prof.add_queue_probe("dead_probe", lambda: 1 // 0)  # raises -> -1
        await prof.start()
        try:
            # accounted work + a forced collection inside the window
            async def spin():
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < 0.01:
                    await asyncio.sleep(0)

            await prof.wrap(spin(), "consensus")
            gc.collect()
            await asyncio.sleep(0.08)
            snap = prof.snapshot()  # before stop() releases the hooks
        finally:
            await prof.stop()
        kinds = {e["kind"] for e in rec.events()}
        assert "loop.lag" in kinds
        assert "loop.busy" in kinds
        assert "loop.gc_pause" in kinds
        assert "loop.queue" in kinds
        q = next(e for e in rec.events() if e["kind"] == "loop.queue")
        assert q["stub_queue"] == 7
        assert q["dead_probe"] == -1
        busy = next(e for e in rec.events() if e["kind"] == "loop.busy")
        assert loopprof.busy_categories(busy).get("consensus", 0) > 0
        assert prof.lag_samples > 0
        assert prof.gc_total_ms >= 0
        assert snap["lag_samples"] > 0
        assert snap["owns_hooks"] is True

    def test_lag_histogram_p90(self):
        prof = LoopProfiler()
        for _ in range(90):
            prof._observe_lag(0.0002)  # 0.2 ms
        for _ in range(10):
            prof._observe_lag(0.2)  # 200 ms
        assert prof.lag_samples == 100
        assert prof.lag_p90_ms() == 0.25  # bucket upper edge
        assert prof.lag_max_ms == pytest.approx(200.0)
        assert prof.lag_p90_ms() <= prof.lag_max_ms

    def test_interval_must_be_positive(self):
        with pytest.raises(ValueError):
            LoopProfiler(interval=0)


class TestAttribution:
    def test_shares_sum_to_interval_and_lag_is_capped(self):
        # 1000 ms interval: 400 ms consensus + 100 ms verify busy, 50 ms
        # GC, 600 ms claimed lag -> capped at the 450 ms unaccounted
        # remainder so double counting can't push the sum past 100%
        events = [
            {"t_ns": 500_000_000, "kind": "loop.busy", "interval_ms": 250.0,
             "consensus_ms": 400.0, "verify_ms": 100.0},
            {"t_ns": 600_000_000, "kind": "loop.gc_pause", "n": 2, "ms": 50.0},
            {"t_ns": 700_000_000, "kind": "loop.lag", "lag_ms": 600.0},
        ]
        att = loopprof.attribution(events, 0, 1_000_000_000)
        assert att["wall_ms"] == 1000.0
        assert att["consensus_pct"] == 40.0
        assert att["verify_pct"] == 10.0
        assert att["gc_pct"] == 5.0
        assert att["loop_lag_pct"] == 45.0
        assert att["idle_pct"] == 0.0
        total = sum(v for k, v in att.items() if k.endswith("_pct"))
        assert total == pytest.approx(100.0, abs=0.5)

    def test_idle_fills_the_remainder(self):
        events = [
            {"t_ns": 100, "kind": "loop.busy", "interval_ms": 250.0,
             "gossip_ms": 100.0},
        ]
        att = loopprof.attribution(events, 0, 1_000_000_000)
        assert att["gossip_pct"] == 10.0
        assert att["idle_pct"] == 90.0

    def test_events_outside_the_interval_are_excluded(self):
        inside = {"t_ns": 500, "kind": "loop.busy", "interval_ms": 1.0, "rpc_ms": 1.0}
        before = {"t_ns": 0, "kind": "loop.busy", "interval_ms": 1.0, "rpc_ms": 99.0}
        after = {"t_ns": 2_000, "kind": "loop.busy", "interval_ms": 1.0, "rpc_ms": 99.0}
        att = loopprof.attribution([before, inside, after], 0, 1_000)
        assert att is not None and "rpc_pct" in att

    def test_none_without_profiler_events(self):
        assert loopprof.attribution([{"t_ns": 5, "kind": "commit"}], 0, 10) is None
        assert loopprof.attribution([], 0, 1_000) is None
        assert loopprof.attribution([], 10, 10) is None  # empty interval
