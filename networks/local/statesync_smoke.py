#!/usr/bin/env python
"""Statesync smoke: an empty 4th node joins a live 3-validator localnet
via snapshot restore — the `make statesync-smoke` acceptance rig.

Flow:
  1. generate a 3-validator `testnet --fast` tree, switch on app
     snapshots ([statesync] snapshot_interval) in every config;
  2. run the validators as OS processes until a snapshot provably exists
     (height > interval + 2);
  3. read the trust root (header hash at a committed height) from node0's
     RPC, generate a 4th EMPTY node home with `[statesync] enable`,
     trust servers = node0+node1 RPC, persistent peers = all validators;
  4. start the joiner and require, within --budget seconds: sync phase
     reaches `caught_up`, the joiner's `earliest_block_height` is ABOVE
     genesis (fell-back-to-replay ⇒ FAIL), its flight recorder shows the
     full statesync.offer→chunk→restore→handover span chain, and it then
     FOLLOWS consensus (head advances ≥ 2 more heights).

With --json the last stdout line carries `statesync_bootstrap_ms`
(measured from the recorder spans) — the number bench.py reports.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from tendermint_tpu.config import load_config, save_config  # noqa: E402
from tendermint_tpu.libs import tracing  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable  # noqa: E402

# the --fast rig commits ~10 blocks/sec: a snapshot lives keep_recent ×
# interval blocks, so 10 × 10 gives the joiner a ~10 s window per
# snapshot (plus re-discovery of fresher ones between candidates)
SNAPSHOT_INTERVAL = 10
SNAPSHOT_KEEP_RECENT = 10


def rpc(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=3) as r:
        return json.load(r)


def heights(ports):
    out = []
    for p in ports:
        try:
            out.append(int(rpc(p, "status")["result"]["sync_info"]["latest_block_height"]))
        except Exception:
            out.append(-1)
    return out


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "wb")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-statesync")
    ap.add_argument("--base-port", type=int, default=29656)
    ap.add_argument("--budget", type=float, default=90.0,
                    help="seconds the joiner gets from spawn to caught_up + follow")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "3", "--output", build,
         "--base-port", str(args.base_port), "--fast"],
        check=True, capture_output=True, timeout=120, cwd=REPO,
    )

    homes = sorted(os.path.join(build, d) for d in os.listdir(build) if d.startswith("node"))
    rpc_ports = []
    for home in homes:
        path = os.path.join(home, "config", "config.toml")
        cfg = load_config(path, home=home)
        cfg.statesync.snapshot_interval = SNAPSHOT_INTERVAL
        cfg.statesync.snapshot_keep_recent = SNAPSHOT_KEEP_RECENT
        cfg.statesync.snapshot_chunk_bytes = 4096
        save_config(cfg, path)
        rpc_ports.append(int(cfg.rpc.laddr.rsplit(":", 1)[1]))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    procs = [spawn(home, env) for home in homes]
    joiner_proc = None
    result, ok = {}, False
    try:
        # validators up + a snapshot provably taken
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = heights(rpc_ports)
            if min(hs) > SNAPSHOT_INTERVAL + 2:
                break
            if any(p.poll() is not None for p in procs):
                print("a validator process died during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"validators never reached snapshot height: {heights(rpc_ports)}",
                  file=sys.stderr)
            return 1
        print(f"validators at {heights(rpc_ports)}; snapshot at {SNAPSHOT_INTERVAL} exists")

        # trust root from node0 (height 2 is long-committed and canonical)
        commit = from_jsonable(rpc(rpc_ports[0], "commit?height=2")["result"])
        trust_hash = commit["signed_header"].header.hash().hex()

        # the 4th, EMPTY node: node0's config shape (fast-rig timeouts,
        # memdb, chain id) with its own ports, statesync on, peers +
        # trust servers wired
        joiner_home = os.path.join(build, "joiner")
        cfg = load_config(os.path.join(homes[0], "config", "config.toml"),
                          home=joiner_home)
        cfg.home = joiner_home
        cfg.base.moniker = "joiner"
        cfg.base.fast_sync = True
        jp = args.base_port + 50
        cfg.p2p.laddr = f"tcp://127.0.0.1:{jp}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{jp + 1}"
        peers = []
        for home in homes:
            c = load_config(os.path.join(home, "config", "config.toml"), home=home)
            nid = subprocess.run(
                [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "show_node_id"],
                capture_output=True, text=True, cwd=REPO, timeout=60,
            ).stdout.strip()
            peers.append(f"{nid}@{c.p2p.laddr.split('://')[-1]}")
        cfg.p2p.persistent_peers = ",".join(peers)
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = ",".join(
            f"127.0.0.1:{p}" for p in rpc_ports[:2]
        )
        cfg.statesync.trust_height = 2
        cfg.statesync.trust_hash = trust_hash
        cfg.statesync.discovery_time = 2.0
        cfg.ensure_dirs()
        save_config(cfg, os.path.join(joiner_home, "config", "config.toml"))
        shutil.copy(os.path.join(homes[0], "config", "genesis.json"),
                    os.path.join(joiner_home, "config", "genesis.json"))

        t_join = time.time()
        joiner_proc = spawn(joiner_home, env)
        jrpc = jp + 1

        # gate 1: caught_up within budget, never having replayed genesis
        caught_up = False
        while time.time() - t_join < args.budget:
            if joiner_proc.poll() is not None:
                print("joiner process died", file=sys.stderr)
                return 1
            try:
                si = rpc(jrpc, "status")["result"]["sync_info"]
            except Exception:
                time.sleep(0.5)
                continue
            if si["sync_phase"] == "caught_up" and int(si["latest_block_height"]) >= 1:
                caught_up = True
                base = int(si["earliest_block_height"])
                break
            time.sleep(0.5)
        if not caught_up:
            print(f"joiner never caught up within {args.budget}s", file=sys.stderr)
            return 1
        bootstrap_wall_s = time.time() - t_join
        if base <= 1:
            print(f"FAIL: joiner replayed from genesis (base={base}) — statesync "
                  "did not carry the bootstrap", file=sys.stderr)
            return 1
        print(f"joiner caught up in {bootstrap_wall_s:.1f}s wall; store base={base} "
              f"(snapshot height, not genesis)")

        # gate 2: recorder proves the offer→chunk→restore→handover chain
        events = rpc(jrpc, "dump_flight_recorder")["result"]["events"]
        boot_ms = tracing.statesync_bootstrap_ms(events)
        if boot_ms is None:
            kinds = sorted({e["kind"] for e in events if str(e["kind"]).startswith("statesync")})
            print(f"FAIL: incomplete statesync span chain (saw {kinds})", file=sys.stderr)
            return 1
        print(f"statesync_bootstrap_ms={boot_ms:.1f} (offer→handover, from recorder spans)")

        # gate 3: the joiner FOLLOWS consensus — commits keep landing
        h0 = int(rpc(jrpc, "status")["result"]["sync_info"]["latest_block_height"])
        follow_deadline = time.time() + max(10.0, args.budget - (time.time() - t_join))
        while time.time() < follow_deadline:
            h = int(rpc(jrpc, "status")["result"]["sync_info"]["latest_block_height"])
            if h >= h0 + 2:
                ok = True
                break
            time.sleep(0.5)
        if not ok:
            print("FAIL: joiner caught up but stopped committing", file=sys.stderr)
            return 1
        print(f"joiner following consensus (height {h0} -> {h}); smoke PASSED")
        result = {
            "statesync_bootstrap_ms": round(boot_ms, 1),
            "bootstrap_wall_s": round(bootstrap_wall_s, 2),
            "snapshot_height": base,
            "joiner_height": h,
            "validator_heights": heights(rpc_ports),
        }
    except KeyboardInterrupt:
        pass
    finally:
        for p in procs + ([joiner_proc] if joiner_proc else []):
            p.send_signal(signal.SIGTERM)
        for p in procs + ([joiner_proc] if joiner_proc else []):
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
