#!/usr/bin/env python
"""Mesh smoke: the sharded verify engine on a CI box — `make mesh-smoke`.

Self-provisions an N-device mesh (default 8) out of virtual host-CPU XLA
devices (`--xla_force_host_platform_device_count`), then proves the two
things MULTICHIP_r05.json only proved in a dryrun:

  1. engine — the sharded fused dispatch produces BIT-IDENTICAL verdicts
     to the single-device path on a mixed valid/invalid batch (liar on
     every shard), on ragged sizes, and through the chunked double-buffer;
     throughput of both paths is measured and reported as
     `sharded_sigs_per_sec` / `single_sigs_per_sec` / `mesh_scaling_ratio`
     (speedup ÷ shards — the dryrun acceptance gate is >= 0.7 on real
     multi-chip hardware; on an oversubscribed CI host the ratio is
     reported, not gated, because 8 virtual devices share ~2 cores).
  2. live node — a real solo-validator Node started with [tpu] mesh = "on"
     must route its commit verification through the sharded engine with
     ZERO call-site changes: the smoke waits for committed blocks and then
     asserts the flight recorder holds `verify.dispatch` events carrying
     `shards=N` on a device-side path.

FAILS on: mesh probe not yielding N shards, any verdict divergence, the
live node committing without a sharded device dispatch, or no blocks at
all.  With --json the last stdout line carries the numbers bench.py
reports (`sharded_sigs_per_sec`, `mesh_scaling_ratio`, `verify_shards`).
"""

import argparse
import asyncio
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def _provision(n_devices: int) -> None:
    """Force n virtual host-CPU XLA devices — must run before jax (or any
    module importing it) initializes a backend."""
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def _mixed_batch(n_sigs: int, n_vals: int):
    """(pubkeys, idxs, msgs, sigs, expect): one invalid signature per shard
    position so liar attribution is exercised on every shard."""
    from tendermint_tpu.crypto.keys import Ed25519PrivKey

    keys = [Ed25519PrivKey.from_secret(b"mesh-smoke-%d" % i) for i in range(n_vals)]
    pks = [k.pub_key().bytes() for k in keys]
    idxs = [i % n_vals for i in range(n_sigs)]
    msgs = [b"mesh-smoke-msg-%d" % i for i in range(n_sigs)]
    sigs = [keys[idxs[i]].sign(msgs[i]) for i in range(n_sigs)]
    expect = [True] * n_sigs
    stride = max(1, n_sigs // 16)
    for j in range(0, n_sigs, stride):  # liars spread across every shard
        sigs[j] = bytes(64)
        expect[j] = False
    return pks, idxs, msgs, sigs, expect


def engine_phase(args) -> dict:
    import numpy as np  # noqa: F401

    from tendermint_tpu.crypto import backend
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier, PubkeyTable

    mesh, shards, reason = backend.resolve_mesh("on", args.devices)
    print(f"mesh probe: shards={shards} ({reason})", flush=True)
    assert shards == args.devices, f"expected {args.devices} shards: {reason}"

    pks, idxs, msgs, sigs, expect = _mixed_batch(args.batch, 16)

    tab_mesh = PubkeyTable(pks, BatchVerifier(mesh=mesh))
    tab_one = PubkeyTable(pks, BatchVerifier())

    t0 = time.perf_counter()
    out_mesh = tab_mesh.verify_indexed(idxs, msgs, sigs)
    print(f"sharded cold dispatch (compile): {time.perf_counter() - t0:.1f}s", flush=True)
    out_one = tab_one.verify_indexed(idxs, msgs, sigs)
    assert out_mesh == expect, "sharded verdicts wrong vs ground truth"
    assert out_mesh == out_one, "sharded verdicts diverge from single-device"

    # ragged sizes (not divisible by shard count) must not leak padding.
    # Sizes are chosen to land in TWO buckets total (args.batch and 16):
    # every distinct sharded bucket is a fresh XLA compile (~60 s cold on
    # the CI host), so the smoke proves raggedness, not compile stamina.
    t0 = time.perf_counter()
    for nn in (args.batch // 2 + 3, 13, 11):
        assert tab_mesh.verify_indexed(idxs[:nn], msgs[:nn], sigs[:nn]) == expect[:nn], nn
    print(f"ragged OK ({time.perf_counter() - t0:.1f}s)", flush=True)

    # chunked double-buffer path, forced, must match too (chunk bucket 16
    # rides the ragged compile; only the donated per-chunk jit is new)
    t0 = time.perf_counter()
    tab_chunk = PubkeyTable(pks, BatchVerifier(mesh=mesh, chunk_size=16))
    tab_chunk.chunked_single_shot = True
    assert tab_chunk.verify_indexed(idxs, msgs, sigs) == expect, "chunked diverges"
    print(f"chunked OK ({time.perf_counter() - t0:.1f}s)", flush=True)

    def best_of(table, k=3):
        best = float("inf")
        for _ in range(k):
            t0 = time.perf_counter()
            table.verify_indexed(idxs, msgs, sigs)
            best = min(best, time.perf_counter() - t0)
        return best

    t_mesh = best_of(tab_mesh)
    t_one = best_of(tab_one)
    speedup = t_one / t_mesh if t_mesh > 0 else 0.0
    return {
        "verify_shards": shards,
        "sharded_sigs_per_sec": round(args.batch / t_mesh, 1),
        "single_sigs_per_sec": round(args.batch / t_one, 1),
        "mesh_speedup_x": round(speedup, 3),
        "mesh_scaling_ratio": round(speedup / shards, 3),
        "verdicts_identical": True,
    }


async def live_node_phase(args, tmp: str) -> dict:
    from tendermint_tpu.config import test_config as make_test_cfg
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
    from tendermint_tpu.types.events import EVENT_NEW_BLOCK, query_for_event
    from tendermint_tpu.types.params import BlockParams, ConsensusParams

    pv = MockPV()
    cfg = make_test_cfg(tmp)
    cfg.rpc.laddr = ""
    cfg.base.db_backend = "memdb"
    cfg.base.proxy_app = "kvstore"
    # the live engine, exactly as node.py wires it — mesh forced on so the
    # virtual CPU devices count as a mesh, every batch takes the device path
    cfg.tpu.enabled = True
    cfg.tpu.mesh = "on"
    cfg.tpu.mesh_devices = args.devices
    cfg.tpu.min_device_batch = 1
    gen = GenesisDoc(
        chain_id="mesh-smoke",
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10)],
        consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
    )
    node = Node(cfg, gen, priv_validator=pv, db_backend="memdb")
    await node.start()
    try:
        sub = await node.event_bus.subscribe(
            "mesh-smoke", query_for_event(EVENT_NEW_BLOCK), buffer=100
        )
        got = 0

        async def consume():
            nonlocal got
            async for _ in sub:
                got += 1
                if got >= args.blocks:
                    return

        await asyncio.wait_for(consume(), args.node_timeout)
    finally:
        await node.stop()
    dispatches = node.flight_recorder.events(kinds=["verify.dispatch"])
    sharded = [
        e for e in dispatches
        if e.get("shards") == args.devices
        and e.get("path") in ("device", "indexed", "chunked", "tabulated")
    ]
    assert dispatches, "live node recorded no verify.dispatch events"
    assert sharded, (
        f"live node never dispatched sharded: {[{k: e.get(k) for k in ('path', 'shards')} for e in dispatches[:8]]}"
    )
    print(
        f"live node: {got} blocks, {len(sharded)}/{len(dispatches)} dispatches sharded over "
        f"{args.devices} devices", flush=True,
    )
    return {
        "live_node_blocks": got,
        "live_node_sharded_dispatches": len(sharded),
        "live_node_sharded_path": True,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--blocks", type=int, default=2)
    ap.add_argument("--node-timeout", type=float, default=120.0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    _provision(args.devices)

    import tempfile

    report = {"mesh_devices": args.devices}
    report.update(engine_phase(args))
    with tempfile.TemporaryDirectory(prefix="mesh-smoke-") as tmp:
        report.update(asyncio.run(live_node_phase(args, tmp)))

    print("MESH SMOKE OK", flush=True)
    if args.json:
        print(json.dumps(report), flush=True)
    else:
        for k, v in report.items():
            print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
