#!/usr/bin/env python
"""Scale smoke: a measured 100-validator live net — the `make scale-smoke`
acceptance rig for the relay gossip topology and maj23 vote aggregation.

This is the first time BASELINE config #2 (100-validator live net) commits
blocks at all: 100 full nodes in ONE process (own switches, real TCP
loopback connections, the verify engine ON), wired in a chordal-ring peer
topology (offsets 1, 2, 4, ... — degree O(log N), diameter O(log N))
instead of a 4950-connection full mesh.  Vote gossip rides the relay
topology (`consensus.gossip_relay_degree`) and the maj23 summary/pull
aggregation — full-mesh per-vote chatter is exactly what wedged this
configuration before (O(N²) frames per round, arXiv:2302.00418's fan-out
wall).

Phases:

  1. throughput — the net must commit >= --blocks CONSECUTIVE heights with
     every node agreeing; `e2e_commits_per_sec_100val` is measured between
     the first and last of those commits (min height across all nodes, so
     a straggler counts).  Gossip wakeup / batch-size / summary / pull
     stats are aggregated from the nodes' flight recorders.
  2. chaos — a 50|50 partition (via each node's LinkPolicyTable) must
     STALL the net (no side has +2/3), heal must recover within
     --recovery-bound, and the PR 5 invariant checker (agreement, no
     height regression) must pass over every node's block store with zero
     violations.

Engine routing is probed, not assumed: with an accelerator attached the
vote batches ride the device kernel; on a CPU-only host the engine's own
min_device_batch routing sends batches to the threaded C host tier
(device dispatch on 2-core CPU XLA is seconds per call — measured, not
guessed).  The JSON reports which path ran (`engine_device_path`).

With --json the last stdout line carries `e2e_commits_per_sec_100val` —
the number bench.py reports.
"""

import argparse
import asyncio
import json
import os
import resource
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def _raise_fd_limit() -> None:
    """~7 chordal connections per node × N nodes × 2 ends plus stores —
    the default 1024 soft limit is the first thing a 100-node process
    trips."""
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))


def chordal_offsets(n: int):
    offsets, k = [], 1
    while k < n:
        offsets.append(k)
        k *= 2
    return offsets


async def build_net(tmp: str, args, cpu_only: bool):
    from tendermint_tpu.config import test_config as make_test_cfg
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
    from tendermint_tpu.types.params import BlockParams, ConsensusParams

    n = args.validators
    pvs = sorted([MockPV() for _ in range(n)], key=lambda pv: pv.address())
    gen = GenesisDoc(
        chain_id=f"scale-{n}val",
        genesis_time_ns=time.time_ns(),
        validators=[GenesisValidator(pv.address(), pv.get_pub_key(), 10) for pv in pvs],
        consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
    )
    nodes = []
    for i, pv in enumerate(pvs):
        cfg = make_test_cfg(os.path.join(tmp, f"n{i}"))
        cfg.rpc.laddr = ""
        cfg.base.db_backend = "memdb"
        cfg.p2p.laddr = "127.0.0.1:0"
        cfg.p2p.max_num_inbound_peers = n + 8
        cfg.p2p.max_num_outbound_peers = max(10, len(chordal_offsets(n)))
        # the chordal wiring IS the topology under test — PEX would top
        # every node back up toward a full mesh and un-measure the relay
        cfg.p2p.pex = False
        # 64-way parallel dialing on a 2-core box: the 3 s default dial
        # timeout fails healthy handshakes under the storm
        cfg.p2p.dial_timeout = 30.0
        # batched frames should ride few packets: the 1 KiB reference
        # default fragments every vote_batch into a packb+seal+drain round
        # per KiB (the cap is the reference's own 64 KiB)
        cfg.p2p.max_packet_msg_payload_size = 32768
        # verify engine ON — the acceptance condition.  On a CPU-only host
        # route batches to the engine's threaded C host tier (its own
        # min_device_batch mechanism); with a chip attached, ride it.
        cfg.tpu.enabled = True
        if cpu_only:
            cfg.tpu.min_device_batch = 1 << 30
        # consensus starts DORMANT behind fastsync and is released onto the
        # formed mesh (see build_net) — a coordinated launch.  Without the
        # gate, 100 consensus instances churn rounds against a half-built
        # mesh and the dial storm never completes (measured: conns dying of
        # pong timeouts under the loop backlog).
        cfg.base.fast_sync = True
        # Python-scale timing: a block's vote aggregation takes tens of
        # seconds at N=100 on a shared 2-core interpreter, and nodes ENTER
        # each height spread over the commit-propagation tail.  Unlike the
        # small-net throughput rigs, timeout_commit must NOT be zeroed:
        # it is the reference's round-start aligner, and without it early
        # committers burn timeout_propose before the slow majority arrives
        # and every height >= 2 decays into nil-prevote round churn
        # (measured: pv=100/pc=92-mostly-nil -> round 1, repeatedly).
        # Vote timeouts cover the aggregation tail so a mixed nil/block
        # wave doesn't nil-cascade; the happy path never waits on them.
        cfg.consensus.timeout_propose = 15.0
        cfg.consensus.timeout_propose_delta = 3.0
        cfg.consensus.timeout_prevote = 10.0
        cfg.consensus.timeout_prevote_delta = 2.0
        cfg.consensus.timeout_precommit = 10.0
        cfg.consensus.timeout_precommit_delta = 2.0
        cfg.consensus.timeout_commit = 15.0
        cfg.consensus.skip_timeout_commit = False
        cfg.consensus.peer_gossip_sleep_duration = 1.0
        cfg.consensus.peer_query_maj23_sleep_duration = 5.0
        cfg.consensus.gossip_relay_degree = args.relay_degree
        # engage the relay whenever there are more peers than the degree —
        # the chordal wiring already bounds the peer set, so the default
        # full-mesh floor (12) would leave the topology untested
        cfg.consensus.gossip_relay_min_peers = args.relay_degree
        cfg.consensus.gossip_relay_debounce = args.debounce
        cfg.consensus.gossip_vote_summary = not args.no_summary
        # scheduler profiler: the first-started node owns the process-wide
        # task/GC accounting hooks (one loop, one GC — libs/loopprof.py);
        # every node still runs its own lag probe.  1 s probes keep 100
        # probe tasks negligible on an already-saturated loop, and the
        # high-rate gossip kinds are sampled 1-in-N so the ring survives a
        # full multi-minute block interval instead of evicting it.
        cfg.instrumentation.loop_probe_interval = args.probe_interval
        cfg.instrumentation.trace_sample_high_rate = args.trace_sample
        # 100 per-node watchdog tickers would add 50+ wakeups/sec to an
        # already loop-bound rig (the exact class PR 6 trimmed); the
        # checker judges this net from outside
        cfg.instrumentation.watchdog = False
        cfg.chaos.enabled = True
        cfg.chaos.seed = args.seed
        nodes.append(Node(cfg, gen, priv_validator=pv, db_backend="memdb"))

    # Coordinated launch: hold every node's consensus dormant behind the
    # fastsync gate while the mesh forms (the caught-up handover interval
    # is raised for the window, then restored — the same
    # statesync→fastsync→consensus machinery a bootstrapping node rides).
    from tendermint_tpu.fastsync import reactor as fs_reactor

    orig_interval = fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL
    fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL = 3600.0
    t0 = time.perf_counter()
    try:
        for node in nodes:
            await node.start()
        # chordal ring: i dials i+1, i+2, i+4, ... (mod n), batched —
        # the loop is quiet (consensus gated), so dials converge fast
        offsets = chordal_offsets(n)

        def edges():
            for i in range(n):
                for off in offsets:
                    j = (i + off) % n
                    yield i, j

        for attempt in range(4):  # re-dial edges that lost the storm
            dials = [
                (i, f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}")
                for i, j in edges()
                if nodes[j].node_key.id not in nodes[i].switch.peers
            ]
            if not dials:
                break
            for k in range(0, len(dials), 32):
                await asyncio.gather(
                    *(nodes[i].switch.dial_peer(addr) for i, addr in dials[k : k + 32]),
                    return_exceptions=True,
                )
            await asyncio.sleep(1.0)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if all(node.switch.num_peers() >= len(offsets) for node in nodes):
                break
            await asyncio.sleep(0.2)
        else:
            raise RuntimeError(
                "peer mesh never converged: "
                f"{sorted(node.switch.num_peers() for node in nodes)[:5]}..."
            )
    finally:
        # release: every fastsync reactor sees itself caught up on its next
        # pass and hands over to consensus on the formed mesh
        fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL = orig_interval
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if all(
            node.consensus is not None and node.consensus.is_running for node in nodes
        ):
            break
        await asyncio.sleep(0.2)
    else:
        held = sum(
            1 for node in nodes if node.consensus is None or not node.consensus.is_running
        )
        raise RuntimeError(f"{held} nodes never switched fastsync→consensus")
    return nodes, time.perf_counter() - t0


def gossip_stats(nodes) -> dict:
    """Aggregate relay/aggregation telemetry from every node's flight
    recorder — the same stream `trace` and the RPC dump serve."""
    wakeups = summaries = pulls = pulled_votes = 0
    batch_sizes = []
    single = 0
    for node in nodes:
        for e in node.flight_recorder.events():
            k = e["kind"]
            if k == "gossip.wakeup":
                # high-rate kind: stored 1-in-N with the factor recorded
                wakeups += e.get("sampled", 1)
            elif k == "gossip.summary":
                summaries += 1
            elif k == "gossip.pull_serve":
                pulls += 1
                pulled_votes += e.get("n", 0)
            elif k == "gossip.votes":
                if e.get("mode") == "batch":
                    batch_sizes.append(e.get("n", 0))
                else:
                    single += 1
    batch_sizes.sort()
    return {
        "wakeups": wakeups,
        "vote_batches": len(batch_sizes),
        "vote_batch_mean": (
            round(sum(batch_sizes) / len(batch_sizes), 2) if batch_sizes else 0
        ),
        "vote_batch_p90": batch_sizes[int(len(batch_sizes) * 0.9)] if batch_sizes else 0,
        "single_vote_frames": single,
        "summaries": summaries,
        "pulls_served": pulls,
        "votes_pulled": pulled_votes,
    }


def profile_net(nodes, dump_dir: str = "") -> dict:
    """The measured answer to "where do the 60 s/block actually go":
    snapshot every node's flight recorder, align them onto one wall
    timeline (libs/tracemerge.py), and decompose each block interval into
    loop-task / GC / loop-lag / idle shares.  On this ONE-process rig the
    first-started node's profiler owns the process-wide spawn/GC hooks,
    so its attribution is the process attribution — the replacement for
    the old "Python-loop-bound" narrative.  Dumps optionally land in
    `dump_dir` (one JSON per node) for offline `trace-net` runs."""
    from tendermint_tpu.libs import tracemerge, tracing

    dumps = []
    for i, node in enumerate(nodes):
        snap = node.flight_recorder.snapshot()
        snap["node"] = f"n{i}"
        dumps.append(snap)
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        for d in dumps:
            with open(os.path.join(dump_dir, f"{d['node']}.json"), "w") as fh:
                json.dump(d, fh)
    out = {}
    lags = sorted(
        node.loop_profiler.lag_p90_ms()
        for node in nodes
        if node.loop_profiler is not None
    )
    if lags:
        out["loop_lag_ms_p90_100val"] = round(lags[len(lags) // 2], 1)
        out["loop_lag_ms_max"] = round(
            max(node.loop_profiler.lag_max_ms for node in nodes
                if node.loop_profiler is not None), 1)
    merged = tracemerge.merge(dumps)
    out["commit_skew_ms_100val"] = merged["commit_skew_ms_p90"]
    out["part_coverage_ms_p90_100val"] = merged["coverage_ms_p90"]
    # how many nodes got MEASURED (wire trace context) rather than
    # landmark-estimated clock alignment in the merge
    out["measured_skew_nodes"] = sum(
        1 for s in merged.get("offset_sources", []) if s == "measured"
    )
    # cross-node net budget from one receiver's events (the stages are
    # per-receiver by construction; any non-proposer-biased node works)
    netb = tracing.net_budget(dumps[0]["events"]) if dumps else None
    if netb:
        out["net_budget"] = netb
        st = netb["stages"]
        out["vote_fanin_ms"] = st.get("vote_fanin", {}).get("p50_ms", -1.0)
        out["part_stream_ms"] = st.get("part_stream", {}).get("p50_ms", -1.0)
        out["gossip_hop_p90_ms"] = netb.get("hop_lat_all_ms", {}).get("p90", -1.0)
        print("net " + tracing.format_net_budget(netb).replace("\n", "\n  "),
              flush=True)
    att = None
    for d in dumps:  # only the hook-owning node carries loop.busy events
        att = tracemerge.median_attribution(tracemerge.attribution_by_height(d))
        if att:
            break
    out["block_attribution_100val"] = att
    slow = tracemerge.slowest_height(merged)
    if slow is not None:
        print(
            f"slowest block on the merged network timeline (height {slow}):",
            flush=True,
        )
        print(tracemerge.format_timeline(merged, [slow]), flush=True)
    if att:
        shares = " ".join(
            f"{k[:-4]}={v}%" for k, v in sorted(att.items()) if k.endswith("_pct")
        )
        print(f"block attribution (median % of block wall time): {shares}", flush=True)
    return out


async def run(args) -> dict:
    import jax

    from tendermint_tpu.chaos import InProcRig, InvariantChecker, RecoveryTimer, Scenario, ScenarioRunner

    cpu_only = all(d.platform == "cpu" for d in jax.devices())
    n = args.validators
    result = {
        "metric": "scale_smoke",
        "validators": n,
        "relay_degree": args.relay_degree,
        "engine_device_path": not cpu_only,
        "failures": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        nodes, startup_s = await build_net(tmp, args, cpu_only)
        result["startup_s"] = round(startup_s, 1)
        result["peers_per_node"] = round(
            sum(node.switch.num_peers() for node in nodes) / n, 1
        )
        print(
            f"net up: {n} validators, ~{result['peers_per_node']} peers/node, "
            f"startup {startup_s:.1f}s, engine "
            f"{'device' if not cpu_only else 'host-tier (CPU-only box)'}",
            flush=True,
        )
        try:
            # -- phase 1: consecutive commits + measured rate --------------
            def min_height():
                return min(node.block_store.height() for node in nodes)

            deadline = time.monotonic() + args.budget
            t_first = time.monotonic()
            while min_height() < 1 and time.monotonic() < deadline:
                await asyncio.sleep(0.5)
                if time.monotonic() - t_first > 30:
                    hs = sorted(node.block_store.height() for node in nodes)
                    print(
                        f"waiting for first commit everywhere: heights "
                        f"min/med/max={hs[0]}/{hs[len(hs) // 2]}/{hs[-1]}",
                        flush=True,
                    )
                    t_first = time.monotonic()
            h0 = min_height()
            if h0 < 1:
                heights = sorted(node.block_store.height() for node in nodes)
                result["failures"].append(f"no first commit within budget: {heights}")
                return result
            t0 = time.monotonic()
            target = h0 + args.blocks
            last_log = 0.0
            while min_height() < target and time.monotonic() < deadline:
                h = min_height()
                if time.monotonic() - last_log > 10:
                    print(f"+{time.monotonic() - t0:6.1f}s height {h}/{target}", flush=True)
                    last_log = time.monotonic()
                await asyncio.sleep(0.25)
            h1 = min_height()
            elapsed = time.monotonic() - t0
            cps = (h1 - h0) / elapsed if elapsed > 0 else 0.0
            result["blocks_committed"] = h1 - h0
            result["e2e_commits_per_sec_100val"] = round(cps, 3)
            result["block_ms"] = round(1000.0 / cps, 1) if cps > 0 else -1
            result["gossip"] = gossip_stats(nodes)
            if h1 < target:
                result["failures"].append(
                    f"only {h1 - h0}/{args.blocks} consecutive blocks within budget"
                )
            print(
                f"committed {h1 - h0} blocks in {elapsed:.1f}s = {cps:.2f} "
                f"commits/sec; gossip {result['gossip']}",
                flush=True,
            )
            # profiler + cross-node trace surface, BEFORE chaos so the
            # partition doesn't pollute the block attribution
            result.update(profile_net(nodes, args.dump_recorders))

            # every height h0..h1 must exist on every node and agree
            checker = InvariantChecker(n)
            for i, node in enumerate(nodes):
                checker.observe_node(i, node)
            agreed = checker.agreed_heights()
            if len([h for h in agreed if h0 <= h <= h1]) < min(args.blocks, h1 - h0):
                result["failures"].append(
                    f"agreement coverage too thin: {len(agreed)} heights cross-checked"
                )

            # -- phase 2: partition/heal chaos at scale --------------------
            if not args.skip_chaos:
                rig = InProcRig(nodes)
                half = n // 2
                text = (
                    "partition "
                    + ",".join(str(i) for i in range(half))
                    + "|"
                    + ",".join(str(i) for i in range(half, n))
                    + " @0"
                )
                scenario = Scenario.parse(text, seed=args.seed)
                result["scenario_fingerprint"] = scenario.fingerprint()[:16]
                await ScenarioRunner(scenario, rig).run()
                print("partitioned 50|50; waiting for stall...", flush=True)
                await asyncio.sleep(2.0)  # drain in-flight gossip
                stall_h = max(node.block_store.height() for node in nodes)
                # one block-time of silence (capped) is proof enough of a
                # stall at multi-minute block cadences
                await asyncio.sleep(
                    min(150.0, max(4.0, 1.2 * result.get("block_ms", 4000) / 1000.0))
                )
                tip = max(node.block_store.height() for node in nodes)
                if tip > stall_h + 1:
                    result["failures"].append(
                        f"commits continued across a 50|50 partition: {stall_h} -> {tip}"
                    )
                else:
                    print(f"partition stalled the net at ~{stall_h}", flush=True)
                for i, node in enumerate(nodes):
                    checker.observe_node(i, node)

                timer = RecoveryTimer()
                timer.mark("heal", min_height())
                await rig.heal()
                heal_deadline = time.monotonic() + args.recovery_bound
                while time.monotonic() < heal_deadline:
                    timer.observe(min_height())
                    if "heal" in timer.recovery_ms:
                        break
                    await asyncio.sleep(0.5)
                ms = timer.recovery_ms.get("heal")
                result["chaos_partition_recovery_ms_100val"] = (
                    round(ms, 1) if ms is not None else -1.0
                )
                if ms is None:
                    result["failures"].append(
                        f"net never recovered within {args.recovery_bound}s of heal"
                    )
                else:
                    print(f"healed; first new commit after {ms:.0f} ms", flush=True)
                for i, node in enumerate(nodes):
                    checker.observe_node(i, node)

            result["agreed_heights"] = len(checker.agreed_heights())
            result["max_height"] = max(node.block_store.height() for node in nodes)
            if checker.violations:
                result["failures"].append(f"invariant violations: {checker.violations}")
            result["violations"] = list(checker.violations)
        finally:
            stop_t0 = time.perf_counter()
            for i in range(0, len(nodes), 10):
                await asyncio.gather(
                    *(node.stop() for node in nodes[i : i + 10] if node.is_running),
                    return_exceptions=True,
                )
            print(f"net stopped in {time.perf_counter() - stop_t0:.1f}s", flush=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=100)
    ap.add_argument("--blocks", type=int, default=10,
                    help="consecutive commits required (and the measure window)")
    ap.add_argument("--relay-degree", type=int, default=6)
    ap.add_argument("--debounce", type=float, default=0.25,
                    help="vote-coalescing linger per relay wakeup (seconds); "
                         "larger windows = fewer, bigger frames (a 2-core "
                         "box stalls in a tiny-frame flood below ~0.25)")
    ap.add_argument("--no-summary", action="store_true",
                    help="disable maj23 aggregation (A/B comparisons)")
    ap.add_argument("--probe-interval", type=float, default=1.0,
                    help="scheduler-profiler probe tick (seconds)")
    ap.add_argument("--trace-sample", type=int, default=8,
                    help="1-in-N sampling for high-rate recorder kinds "
                         "(gossip.wakeup) so the ring survives a block interval")
    ap.add_argument("--dump-recorders", default="",
                    help="directory to write every node's recorder dump "
                         "(one JSON per node, trace-net input)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--budget", type=float, default=2200.0,
                    help="seconds for startup-to-last-commit of phase 1 "
                         "(a 2-core CPU box runs ~2-3 min/block at N=100; "
                         "multi-core/TPU hosts are far faster)")
    ap.add_argument("--recovery-bound", type=float, default=420.0)
    ap.add_argument("--skip-chaos", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    _raise_fd_limit()
    result = asyncio.run(run(args))
    failures = result.pop("failures", [])
    if failures:
        print("SCALE SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
    else:
        print(
            f"scale smoke ok: {result['validators']} validators, "
            f"{result.get('blocks_committed', 0)} consecutive commits at "
            f"{result.get('e2e_commits_per_sec_100val', 0)} commits/sec, "
            f"agreement over {result.get('agreed_heights', 0)} heights, "
            f"loop lag p90 {result.get('loop_lag_ms_p90_100val', '?')} ms, "
            f"commit skew p90 {result.get('commit_skew_ms_100val', '?')} ms, "
            f"heal recovery {result.get('chaos_partition_recovery_ms_100val', 'skipped')} ms"
        )
    if args.json:
        result["ok"] = not failures
        print(json.dumps(result))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
