#!/usr/bin/env python
"""Finality smoke: the consensus-pipeline A/B acceptance rig against a
real 4-validator multi-process localnet — `make finality-smoke`.

Two arms, each a fresh `testnet --fast` build (the --fast rig runs on
memdb, so an in-place restart cannot carry the chain across arms):

  serial     pipeline_delivery = pipeline_speculative_assembly = False on
             every node: height H+1 cannot start until H's ABCI delivery
             completes on the receive routine (pre-pipeline behaviour)
  pipelined  both knobs ON (the shipping default): ABCI finalize runs on
             a spawned delivery task, H+1's propose overlaps H's
             finalize, the proposer's part-set is speculatively
             pre-built — then a tools/loadgen.py firehose window measures
             finality under ingress pressure

Each arm measures commit-to-commit latency and the per-stage budget
(propose / prevote / precommit / commit_persist / finalize /
next_propose) from node0's flight recorder via `dump_flight_recorder`
seq watermarks, while the chaos invariant checker scrapes /status +
/blockchain from every node underneath (agreement, no height
regression).

FAILS on: any checker violation; either arm too stalled to budget;
pipelined idle commit-to-commit p50 >= --latency-bound (default 100 ms);
pipelined p50 regressing past --regress-tolerance x the serial p50; a
stall under the firehose; too few cross-checked heights.

With --json the last stdout line carries `commit_to_commit_p50_ms`,
`commit_to_commit_p90_ms`, `finality_under_load_p50_ms`, both arms'
stage budgets, and the pipelined arm's cross-node net budget
(`vote_fanin_ms`, `part_stream_ms`, `gossip_hop_p90_ms` plus the full
`net_budget` breakdown) — the numbers bench.py reports as bench_finality.
"""

import argparse
import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import tendermint_tpu.store  # noqa: E402,F401 — registers BlockMeta with the codec
import tendermint_tpu.types  # noqa: E402,F401 — registers Block types
from tendermint_tpu.chaos.checker import InvariantChecker  # noqa: E402
from tendermint_tpu.config import load_config, save_config  # noqa: E402
from tendermint_tpu.libs import tracing  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable  # noqa: E402
from tendermint_tpu.tools import loadgen  # noqa: E402


def rpc(port: int, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def scrape(checker: InvariantChecker, ports) -> None:
    for i, p in enumerate(ports):
        h = height_of(p)
        checker.observe_height(i, h)
        if h is None or h < 1:
            continue
        try:
            metas = from_jsonable(
                rpc(p, f"blockchain?min_height={max(1, h - 19)}&max_height={h}")["result"]
            )["block_metas"]
        except Exception:
            continue
        for meta in metas:
            checker.observe_block_hash(i, meta.header.height, meta.block_id.hash)


def recorder_seq(port: int) -> int:
    """Current flight-recorder watermark: pass it back as `since` to dump
    only events recorded after this instant."""
    snap = rpc(port, "dump_flight_recorder?kinds=none")["result"]
    return int(snap.get("next_seq", 0))


def recorder_events(port: int, since: int):
    snap = rpc(port, f"dump_flight_recorder?since={since}", timeout=10.0)["result"]
    return snap.get("events", [])


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def arm_pipeline(homes, on: bool) -> None:
    """Flip the pipeline knobs on every node's config.toml."""
    for home in homes:
        path = os.path.join(home, "config", "config.toml")
        cfg = load_config(path, home=home)
        cfg.consensus.pipeline_delivery = on
        cfg.consensus.pipeline_speculative_assembly = on
        save_config(cfg, path)


def build_testnet(build: str, base_port: int, pipeline_on: bool):
    """Fresh 4-val --fast testnet with the pipeline knobs armed the
    requested way on every node.  Returns (homes, ports)."""
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build,
         "--base-port", str(base_port), "--fast"],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [base_port + 10 * i + 1 for i in range(4)]
    arm_pipeline(homes, on=pipeline_on)
    return homes, ports


def start_net(homes, env, ports):
    """Spawn all nodes and wait for every height to reach 1.  On failure
    the spawned processes are torn down before raising — the caller never
    sees them, so it cannot clean them up itself."""
    procs = [spawn(h, env) for h in homes]
    try:
        deadline = time.time() + 120.0
        while time.time() < deadline:
            hs = [height_of(p) for p in ports]
            if all(h is not None and h >= 1 for h in hs):
                return procs
            if any(p.poll() is not None for p in procs):
                raise RuntimeError("a node died during startup")
            time.sleep(0.5)
        raise RuntimeError(
            f"startup timeout: heights {[height_of(p) for p in ports]}"
        )
    except BaseException:
        stop_net(procs)
        raise


def stop_net(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(10)
        except subprocess.TimeoutExpired:
            p.kill()


def measure_budget(ports, checker, window: float):
    """Scrape the checker for `window` seconds, then decompose node0's
    recorder events from the window into both budgets: the local stage
    budget and the cross-node net budget (proposal propagation, part
    stream, vote fan-in, hop latencies — wire-level trace context)."""
    mark = recorder_seq(ports[0])
    deadline = time.time() + window
    while time.time() < deadline:
        scrape(checker, ports)
        time.sleep(0.4)
    events = recorder_events(ports[0], mark)
    return tracing.stage_budget(events), tracing.net_budget(events)


async def _load_phase(ports, checker, args):
    """Firehose + concurrent checker scraping on one loop (the scraper
    hops to a thread per poll so the loadgen workers keep the loop)."""
    targets = [f"127.0.0.1:{p}" for p in ports]
    stop = asyncio.Event()

    async def scraper():
        while not stop.is_set():
            await asyncio.get_event_loop().run_in_executor(
                None, scrape, checker, ports
            )
            try:
                await asyncio.wait_for(stop.wait(), 0.5)
            except asyncio.TimeoutError:
                pass

    scr = asyncio.create_task(scraper())
    try:
        result = await loadgen.run_load(
            targets,
            duration=args.load_duration,
            rate=0.0,  # as fast as the connections go: the firehose
            connections=args.connections,
            tx_bytes=args.tx_bytes,
            mode="sync",
            fee=1,
            monitor_target=targets[0],
        )
    finally:
        stop.set()
        await scr
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-finality")
    ap.add_argument("--base-port", type=int, default=31956)
    ap.add_argument("--measure", type=float, default=8.0,
                    help="idle measurement window per arm (seconds)")
    ap.add_argument("--load-duration", type=float, default=8.0)
    ap.add_argument("--connections", type=int, default=8)
    ap.add_argument("--tx-bytes", type=int, default=192)
    ap.add_argument("--latency-bound", type=float, default=100.0,
                    help="max pipelined idle commit-to-commit p50 (ms) — "
                    "the sub-second-finality hard number at 4 validators")
    ap.add_argument("--regress-tolerance", type=float, default=1.25,
                    help="pipelined p50 must stay <= tolerance x serial p50 "
                    "(idle --fast blocks are empty, so the arms differ by "
                    "scheduling noise; a real re-serialization would add the "
                    "whole finalize span and blow well past this)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    # one checker per arm: each arm is a fresh chain from genesis, so a
    # shared checker would see the height reset as a regression
    checker_serial = InvariantChecker(4)
    checker = InvariantChecker(4)
    result = {}
    ok = False
    procs = []
    try:
        # -- arm A: serial baseline ------------------------------------
        homes, ports = build_testnet(build, args.base_port, pipeline_on=False)
        procs = start_net(homes, env, ports)
        print(f"serial arm ready, heights {[height_of(p) for p in ports]}")
        budget_serial, _ = measure_budget(ports, checker_serial, args.measure)
        stop_net(procs)
        procs = []
        if budget_serial:
            print("serial " + tracing.format_budget(budget_serial).replace("\n", "\n  "))

        # -- arm B: pipelined (the shipping default) -------------------
        homes, ports = build_testnet(build, args.base_port, pipeline_on=True)
        procs = start_net(homes, env, ports)
        print(f"pipelined arm ready, heights {[height_of(p) for p in ports]}")
        budget_on, net_on = measure_budget(ports, checker, args.measure)
        if budget_on:
            print("pipelined " + tracing.format_budget(budget_on).replace("\n", "\n  "))
        if net_on:
            print("pipelined " + tracing.format_net_budget(net_on).replace("\n", "\n  "))

        # firehose window: finality under ingress pressure
        mark = recorder_seq(ports[0])
        load = asyncio.run(_load_phase(ports, checker, args))
        budget_load = tracing.stage_budget(recorder_events(ports[0], mark))
        print(
            f"firehose: offered {load['offered_tps']}/s, accepted "
            f"{load['tx_ingress_sustained_tps']}/s, "
            f"{load['commits_under_load']} commits under load"
        )
        if budget_load:
            print("under-load " + tracing.format_budget(budget_load).replace("\n", "\n  "))

        p50_serial = budget_serial["commit_to_commit_p50_ms"] if budget_serial else -1.0
        p50_on = budget_on["commit_to_commit_p50_ms"] if budget_on else -1.0
        p90_on = budget_on["commit_to_commit_p90_ms"] if budget_on else -1.0
        p50_load = budget_load["commit_to_commit_p50_ms"] if budget_load else -1.0
        net_stages = (net_on or {}).get("stages", {})
        result = {
            "metric": "finality_smoke",
            "commit_to_commit_p50_ms": p50_on,
            "commit_to_commit_p90_ms": p90_on,
            "commit_to_commit_p50_ms_serial": p50_serial,
            "finality_under_load_p50_ms": p50_load,
            "vote_fanin_ms": net_stages.get("vote_fanin", {}).get("p50_ms", -1.0),
            "part_stream_ms": net_stages.get("part_stream", {}).get("p50_ms", -1.0),
            "gossip_hop_p90_ms": (net_on or {}).get(
                "hop_lat_all_ms", {}
            ).get("p90", -1.0),
            "budget_serial": budget_serial,
            "budget_pipelined": budget_on,
            "budget_under_load": budget_load,
            "net_budget": net_on,
            "offered_tps": load["offered_tps"],
            "tx_ingress_sustained_tps": load["tx_ingress_sustained_tps"],
            "commits_under_load": load["commits_under_load"],
            "heights": [height_of(p) for p in ports],
            **checker.summary(),
        }

        failures = []
        if checker_serial.violations:
            failures.append(
                f"invariant violations (serial arm): {checker_serial.violations}"
            )
        if checker.violations:
            failures.append(f"invariant violations: {checker.violations}")
        if budget_serial is None:
            failures.append("serial arm produced no complete span chains")
        if budget_on is None:
            failures.append("pipelined arm produced no complete span chains")
        if p50_on >= 0 and p50_on >= args.latency_bound:
            failures.append(
                f"pipelined commit-to-commit p50 {p50_on} ms >= "
                f"{args.latency_bound} ms bound"
            )
        if p50_on >= 0 and p50_serial >= 0 and p50_on > args.regress_tolerance * p50_serial:
            failures.append(
                f"pipelined p50 {p50_on} ms regressed past "
                f"{args.regress_tolerance}x serial baseline {p50_serial} ms"
            )
        if load["commits_under_load"] < 2:
            failures.append("consensus stalled under the firehose")
        if budget_load is None:
            failures.append("no complete span chains under load")
        if len(checker.agreed_heights()) < 3:
            failures.append("too few heights cross-checked for agreement")
        if failures:
            print("FINALITY SMOKE FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
        else:
            print(
                f"finality smoke ok: pipelined commit-to-commit p50 "
                f"{p50_on} ms (serial {p50_serial} ms, bound "
                f"{args.latency_bound} ms), under-load p50 {p50_load} ms, "
                f"agreement over {len(checker.agreed_heights())} heights"
            )
            ok = True
    finally:
        stop_net(procs)
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
