#!/usr/bin/env python
"""Disk smoke: storage-fault chaos against a real 4-validator multi-process
localnet — the `make disk-smoke` acceptance rig for ISSUE 15.

Scenario (seeded; parsed twice, identical fingerprints asserted):

    rot 3 blockstore h=3 @2     one byte of node3's stored block 3 rots
                                (persistent, via unsafe_chaos_rot)
    disk 2 enospc @8~0.5        every write on node2 returns ENOSPC
    disk 2 heal @16             the volume "recovers" (policy cleared)
    kill 2 @18                  the operator bounces the halted node
    restart 2 @20               crash recovery + catchup

What must hold (checker violations fail the rig):

  self-healing   node3's integrity scan (unsafe_store_integrity_scan)
                 DETECTS the rot, quarantines height 3, re-fetches the
                 block from peers through the fastsync channel, and ends
                 with `/block?height=3` serving a copy whose recomputed
                 hash matches the rest of the net — measured as
                 `disk_fault_recovery_ms` (rot -> verified refill);
                 `store_integrity_scan_ms` comes from the scan report
  clean halt     node2 under ENOSPC stops committing WITHOUT the
                 CONSENSUS FAILURE!!! banner (asserted against its log),
                 keeps answering `/status` and `/health` (the read path
                 stays up), and its watchdog raises the `disk_fault`
                 alarm as CRITICAL while the rest of the net keeps
                 committing (3 of 4 is +2/3)
  recovery       after heal + restart, node2 rejoins and commits past its
                 pre-fault tip inside --recovery-bound
                 (`enospc_recovery_ms`)
  integrity      every scraped `/block` body re-hashes to the meta hash
                 the node claims for it (observe_served_block) — a node
                 serving corrupted bytes as a valid block is a violation
  agreement      the standard checker invariants over every observation

With --json the last stdout line carries the measured numbers for
`bench.py bench_disk`.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import tendermint_tpu.store  # noqa: E402,F401 — registers BlockMeta with the codec
import tendermint_tpu.types  # noqa: E402,F401 — registers Block/evidence types
from tendermint_tpu.chaos.checker import InvariantChecker, RecoveryTimer  # noqa: E402
from tendermint_tpu.chaos.scenario import Scenario  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable  # noqa: E402

SCENARIO = """
rot 3 blockstore h=3 @2
disk 2 enospc @8~0.5
disk 2 heal @16
kill 2 @18
restart 2 @20
"""

ROT_HEIGHT = 3


def rpc(port: int, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def rpc_call(port: int, method: str, **params):
    qs = urllib.parse.urlencode({k: str(v) for k, v in params.items()})
    return rpc(port, f"{method}?{qs}" if qs else method)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def health_of(port: int):
    try:
        return rpc(port, "health")["result"]
    except Exception:
        return None


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-disk")
    ap.add_argument("--base-port", type=int, default=31656)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--recovery-bound", type=float, default=45.0,
                    help="max seconds for refill / restart recovery")
    ap.add_argument("--budget", type=float, default=90.0,
                    help="seconds after the last fault for recovery checks")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    scenario = Scenario.parse(SCENARIO, seed=args.seed)
    assert scenario.fingerprint() == Scenario.parse(SCENARIO, seed=args.seed).fingerprint(), \
        "scenario resolution is not deterministic"
    timeline = scenario.timeline()
    print(f"scenario fingerprint {scenario.fingerprint()[:16]} (seed {args.seed}):")
    for ev in timeline:
        print(f"  {ev.describe()}")

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build, "--base-port", str(args.base_port),
         "--fast", "--db-backend", "sqlite",
         "--chaos", "--chaos-seed", str(args.seed)],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [args.base_port + 10 * i + 1 for i in range(4)]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [spawn(h, env) for h in homes]

    checker = InvariantChecker(4)
    restart_timer = RecoveryTimer()
    result = {}
    ok = False
    live = [True] * 4
    try:
        # readiness: all four answer and pass the rot height
        deadline = time.time() + 120.0
        while time.time() < deadline:
            hs = [height_of(p) for p in ports]
            if all(h is not None and h >= ROT_HEIGHT + 1 for h in hs):
                break
            if any(p.poll() is not None for p in procs):
                print("a node died during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"startup timeout: heights {[height_of(p) for p in ports]}",
                  file=sys.stderr)
            return 1
        print(f"localnet ready, heights {[height_of(p) for p in ports]}")

        state = {
            "scan_report": None,
            "rot_t": None,
            "refill_done_t": None,
            "rot_alarm_seen": False,
            "enospc_t": None,
            "halt_confirmed": False,
            "enospc_alarm_seen": False,
        }

        def scrape():
            hs = []
            for i, p in enumerate(ports):
                h = height_of(p)
                hs.append(h)
                checker.observe_height(i, h)
                if h is None or h < 1:
                    continue
                try:
                    metas = from_jsonable(
                        rpc(p, f"blockchain?min_height={max(1, h - 9)}&max_height={h}")
                        ["result"]
                    )["block_metas"]
                except Exception:
                    continue
                for meta in metas:
                    checker.observe_block_hash(i, meta.header.height, meta.block_id.hash)
            known = [h for h in hs if h is not None]
            if known:
                restart_timer.observe(
                    min(h for j, h in enumerate(hs) if live[j] and h is not None)
                    if all(live[j] and hs[j] is not None for j in range(4))
                    else None
                )
            return hs

        def observe_served(i: int, height: int) -> bool:
            """Fetch the FULL block + the claimed meta hash; feed the
            served-corruption invariant.  Returns True when the node
            served a block for the height."""
            p = ports[i]
            try:
                blk = from_jsonable(rpc(p, f"block?height={height}")["result"])["block"]
                meta = from_jsonable(
                    rpc(p, f"blockchain?min_height={height}&max_height={height}")
                    ["result"]
                )["block_metas"]
            except Exception:
                return False
            if blk is None or not meta:
                return False
            checker.observe_served_block(
                i, height, meta[0].block_id.hash, blk.hash()
            )
            return True

        def poll_faults(now):
            # node3: refill completion = storage_info pending empty AND the
            # block is served again AND it re-hashes to the claimed meta
            if state["rot_t"] is not None and state["refill_done_t"] is None:
                try:
                    sinfo = rpc(ports[3], "storage_info")["result"]
                except Exception:
                    sinfo = None
                if sinfo is not None:
                    if not state["rot_alarm_seen"]:
                        h3 = health_of(ports[3])
                        if h3 and "disk_fault" in h3.get("alarms", {}):
                            state["rot_alarm_seen"] = True
                            print(f"  watchdog: node3 raised disk_fault on the rot")
                    pending = sinfo.get("refill", {}).get("pending", [])
                    quarantined = sinfo.get("blockstore", {}).get("quarantined", [])
                    if not pending and not quarantined and observe_served(3, ROT_HEIGHT):
                        state["refill_done_t"] = now
                        print(f"  node3 refilled height {ROT_HEIGHT} from peers "
                              f"({(now - state['rot_t']) * 1000:.0f} ms after rot)")
            # node2 under ENOSPC: read path must stay up, alarm critical,
            # no new commits
            if state["enospc_t"] is not None and not state["halt_confirmed"]:
                st = height_of(ports[2])
                h2 = health_of(ports[2])
                if st is not None and h2 is not None:
                    alarms = h2.get("alarms", {})
                    if ("disk_fault" in alarms
                            and alarms["disk_fault"]["severity"] == "critical"):
                        state["enospc_alarm_seen"] = True
                        state["halt_confirmed"] = True
                        print(f"  watchdog: node2 disk_fault CRITICAL with the "
                              f"read path still serving (/status answered {st})")

        # -- execute the timeline, scraping between events ------------------
        t0 = time.time()
        for ev in timeline:
            while time.time() < t0 + ev.t:
                scrape()
                poll_faults(time.time())
                time.sleep(0.4)
            print(f"+{time.time() - t0:6.2f}s executing {ev.describe()}")
            if ev.action == "rot":
                node = ev.args["node"]
                rpc_call(ports[node], "unsafe_chaos_rot", height=ev.args["height"])
                state["rot_t"] = time.time()
                # the debug-triggered integrity scan: detect + quarantine +
                # kick the peer refill
                report = rpc_call(ports[node], "unsafe_store_integrity_scan")["result"]
                state["scan_report"] = report
                print(f"  integrity scan: checked={report['checked']} "
                      f"corrupt={report['corrupt']} in {report['ms']} ms")
                if ev.args["height"] not in report["corrupt"]:
                    checker.violations.append(
                        f"integrity scan MISSED the injected rot at height "
                        f"{ev.args['height']}: {report}"
                    )
            elif ev.action == "disk":
                node = ev.args["node"]
                if ev.args["kind"] == "heal":
                    rpc_call(ports[node], "unsafe_chaos_disk", kind="heal",
                             store=ev.args["store"])
                else:
                    rpc_call(ports[node], "unsafe_chaos_disk",
                             kind=ev.args["kind"], store=ev.args["store"],
                             p=ev.args["p"])
                    state["enospc_t"] = time.time()
            elif ev.action == "kill":
                i = ev.args["node"]
                procs[i].send_signal(signal.SIGKILL)
                procs[i].wait(10)
                live[i] = False
            elif ev.action == "restart":
                i = ev.args["node"]
                baseline = max(
                    h for j, p in enumerate(ports) if live[j]
                    for h in [height_of(p)] if h is not None
                )
                procs[i] = spawn(homes[i], env)
                live[i] = True
                restart_timer.mark("restart", baseline)

        # -- recovery within the budget -------------------------------------
        deadline = time.time() + args.budget
        while time.time() < deadline:
            scrape()
            poll_faults(time.time())
            done = (
                state["refill_done_t"] is not None
                and "restart" in restart_timer.recovery_ms
            )
            if done:
                # node2 healthy again?
                h2 = health_of(ports[2])
                if h2 is not None and "disk_fault" not in h2.get("alarms", {}):
                    break
            time.sleep(0.4)

        # -- verdicts --------------------------------------------------------
        if state["scan_report"] is None:
            checker.violations.append("integrity scan never ran")
        if state["refill_done_t"] is None:
            checker.violations.append(
                f"quarantined block {ROT_HEIGHT} was never refilled from peers"
            )
        elif (state["refill_done_t"] - state["rot_t"]) > args.recovery_bound:
            checker.violations.append(
                f"refill took {state['refill_done_t'] - state['rot_t']:.1f}s "
                f"(bound {args.recovery_bound}s)"
            )
        if not state["enospc_alarm_seen"]:
            checker.violations.append(
                "node2 never raised a critical disk_fault alarm under ENOSPC"
            )
        if "restart" not in restart_timer.recovery_ms:
            checker.violations.append(
                "node2 never rejoined consensus after heal + restart"
            )
        # a clean halt never prints the consensus-failure banner
        log2 = open(os.path.join(homes[2], "node.log"), "rb").read()
        if b"CONSENSUS FAILURE" in log2:
            checker.violations.append(
                "node2 hit CONSENSUS FAILURE!!! under ENOSPC — the storage "
                "fault escaped the clean-halt path"
            )
        if b"consensus halted on storage fault" not in log2:
            checker.violations.append(
                "node2's log carries no attributed storage halt"
            )
        # final integrity pass over every live node's served blocks
        tip = min(h for h in (height_of(p) for p in ports) if h is not None)
        for i in range(4):
            for h in range(max(1, tip - 4), tip + 1):
                observe_served(i, h)

        checker.raise_if_violated()
        ok = True
        result = {
            "metric": "disk_smoke",
            "ok": True,
            "seed": args.seed,
            "fingerprint": scenario.fingerprint()[:16],
            "disk_fault_recovery_ms": round(
                (state["refill_done_t"] - state["rot_t"]) * 1000.0, 1
            ),
            "store_integrity_scan_ms": state["scan_report"]["ms"],
            "scan_checked": state["scan_report"]["checked"],
            "enospc_recovery_ms": round(restart_timer.recovery_ms["restart"], 1),
            "heights": [height_of(p) for p in ports],
            "heights_checked": len(checker.agreed_heights()),
        }
        print(f"disk smoke OK: refill {result['disk_fault_recovery_ms']} ms, "
              f"scan {result['store_integrity_scan_ms']} ms, "
              f"restart recovery {result['enospc_recovery_ms']} ms, "
              f"{result['heights_checked']} heights checked")
        return 0
    except AssertionError as e:
        print(f"INVARIANT VIOLATION:\n{e}", file=sys.stderr)
        return 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(15)
            except subprocess.TimeoutExpired:
                p.kill()
        if args.json:
            print(json.dumps(result if ok else {"metric": "disk_smoke", "ok": False}))


if __name__ == "__main__":
    sys.exit(main())
