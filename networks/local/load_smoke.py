#!/usr/bin/env python
"""Load smoke: the tx-ingress firehose against a real 4-validator
multi-process localnet — the `make load-smoke` acceptance rig for the
overload-robustness layer.

Three phases against QoS-configured nodes (per-source RPC rate limit,
bounded in-flight broadcasts, commit-waiter cap, mempool sig_precheck +
priority eviction, per-peer gossip pacing):

  idle      measure the net's unloaded commit rate
  firehose  tendermint_tpu/tools/loadgen.py drives signed-tx envelopes at
            every node's broadcast endpoint as fast as the connections go
            — by construction >= 2x what admission control accepts —
            while the PR 5 chaos invariant checker scrapes /status +
            /blockchain from every node underneath (agreement, no height
            regression); commit-latency-under-load percentiles come from
            node0's flight recorder
  recover   firehose off; the commit rate must return to within 2x idle

FAILS on: any checker violation; a commit stall under load; rejections
WITHOUT explicit overload errors (silent drops: transport-error share of
offered > 5%); offered < 2x accepted (the firehose never saturated
admission); unrecovered post-firehose commit rate.

With --json the last stdout line carries `tx_ingress_sustained_tps` and
`commit_latency_under_load_ms` — the numbers bench.py reports.
"""

import argparse
import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import tendermint_tpu.store  # noqa: E402,F401 — registers BlockMeta with the codec
import tendermint_tpu.types  # noqa: E402,F401 — registers Block types
from tendermint_tpu.chaos.checker import InvariantChecker  # noqa: E402
from tendermint_tpu.config import load_config, save_config  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable  # noqa: E402
from tendermint_tpu.tools import loadgen  # noqa: E402


def rpc(port: int, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def scrape(checker: InvariantChecker, ports) -> None:
    for i, p in enumerate(ports):
        h = height_of(p)
        checker.observe_height(i, h)
        if h is None or h < 1:
            continue
        try:
            metas = from_jsonable(
                rpc(p, f"blockchain?min_height={max(1, h - 19)}&max_height={h}")["result"]
            )["block_metas"]
        except Exception:
            continue
        for meta in metas:
            checker.observe_block_hash(i, meta.header.height, meta.block_id.hash)


def commit_rate(ports, window: float, checker: InvariantChecker) -> float:
    """Blocks/sec over `window` seconds (max known tip), scraping the
    checker along the way."""
    start = None
    deadline = time.time() + window
    while time.time() < deadline:
        scrape(checker, ports)
        tips = [h for h in (height_of(p) for p in ports) if h is not None]
        if tips and start is None:
            start = (time.time(), max(tips))
        time.sleep(0.4)
    tips = [h for h in (height_of(p) for p in ports) if h is not None]
    if start is None or not tips:
        return 0.0
    dt = time.time() - start[0]
    return (max(tips) - start[1]) / dt if dt > 0 else 0.0


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def poll_status_health(ports, health_seen) -> None:
    """Sample every node's /status `health` block (the watchdog verdict):
    the rig asserts the node SELF-reports degradation under the firehose
    — shedding alone could be a node lying to itself about being fine."""
    for i, p in enumerate(ports):
        try:
            st = rpc(p, "status")["result"]
        except Exception:
            continue
        h = st.get("health")
        if h is None:
            continue
        health_seen["block_present"] = True
        if h.get("verdict") != "ok":
            health_seen["degraded"].update(
                f"node{i}:{a}" for a in h.get("alarms", ["<no-alarm-name>"])
            )


async def _load_phase(ports, checker, args, health_seen):
    """Run the firehose and the checker scraper concurrently on one loop
    (the scraper hops to a thread per poll so the loadgen workers keep
    the loop)."""
    targets = [f"127.0.0.1:{p}" for p in ports]
    stop = asyncio.Event()

    def _scrape_once():
        scrape(checker, ports)
        poll_status_health(ports, health_seen)

    async def scraper():
        while not stop.is_set():
            await asyncio.get_event_loop().run_in_executor(None, _scrape_once)
            try:
                await asyncio.wait_for(stop.wait(), 0.5)
            except asyncio.TimeoutError:
                pass

    scr = asyncio.create_task(scraper())
    try:
        result = await loadgen.run_load(
            targets,
            duration=args.load_duration,
            rate=0.0,  # as fast as the connections go: the firehose
            connections=args.connections,
            tx_bytes=args.tx_bytes,
            mode="sync",
            fee=1,  # nonzero priority exercises the fee lane end to end
            monitor_target=targets[0],
        )
    finally:
        stop.set()
        await scr
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-load")
    ap.add_argument("--base-port", type=int, default=31656)
    ap.add_argument("--idle", type=float, default=6.0)
    ap.add_argument("--load-duration", type=float, default=15.0)
    ap.add_argument("--recover", type=float, default=10.0)
    ap.add_argument("--connections", type=int, default=16)
    ap.add_argument("--tx-bytes", type=int, default=192)
    ap.add_argument("--rate-limit", type=float, default=25.0,
                    help="per-source broadcast rate limit configured on each node "
                    "(tx/sec) — sized so even a slow single-host client "
                    "(~300 req/s on 2 cores) overruns the 4-node admission "
                    "ceiling by >= 2x")
    ap.add_argument("--latency-bound", type=float, default=10_000.0,
                    help="max p90 commit interval under load (ms)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build,
         "--base-port", str(args.base_port), "--fast"],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [args.base_port + 10 * i + 1 for i in range(4)]

    # arm the full QoS surface on every node: the rig is only honest if
    # the machinery under test is ON
    for home in homes:
        path = os.path.join(home, "config", "config.toml")
        cfg = load_config(path, home=home)
        cfg.mempool.sig_precheck = True
        cfg.mempool.size = 2000
        cfg.rpc.broadcast_rate = args.rate_limit
        cfg.rpc.broadcast_rate_burst = int(args.rate_limit)
        cfg.rpc.max_broadcast_inflight = 256
        cfg.rpc.max_commit_waiters = 16
        save_config(cfg, path)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [spawn(h, env) for h in homes]

    checker = InvariantChecker(4)
    result = {}
    ok = False
    try:
        deadline = time.time() + 120.0
        while time.time() < deadline:
            hs = [height_of(p) for p in ports]
            if all(h is not None and h >= 1 for h in hs):
                break
            if any(p.poll() is not None for p in procs):
                print("a node died during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"startup timeout: heights {[height_of(p) for p in ports]}",
                  file=sys.stderr)
            return 1
        print(f"localnet ready, heights {[height_of(p) for p in ports]}")

        idle_cps = commit_rate(ports, args.idle, checker)
        print(f"idle commit rate: {idle_cps:.2f} blocks/sec")

        health_seen = {"block_present": False, "degraded": set()}
        t0 = time.time()
        load = asyncio.run(_load_phase(ports, checker, args, health_seen))
        load_wall = time.time() - t0
        tip_after_load = max(
            (h for h in (height_of(p) for p in ports) if h is not None), default=0
        )
        print(
            f"firehose {load_wall:.1f}s: offered {load['offered_tps']}/s, "
            f"accepted {load['tx_ingress_sustained_tps']}/s, throttled "
            f"{load['throttled']}, rejected {load['rejected']}, transport "
            f"errors {load['transport_errors']}, {load['commits_under_load']} "
            f"commits under load, latency {load['commit_latency_under_load_ms']}"
        )

        recover_cps = commit_rate(ports, args.recover, checker)
        print(f"recovery commit rate: {recover_cps:.2f} blocks/sec "
              f"(idle was {idle_cps:.2f})")
        if health_seen["degraded"]:
            print(f"self-reported degradation under load: "
                  f"{sorted(health_seen['degraded'])}")
        # the degradation must CLEAR once the firehose is off — poll past
        # the recovery window for every node to report ok again (mempool
        # drains as blocks commit; lag subsides)
        health_recovered = False
        clear_deadline = time.time() + 20.0
        while time.time() < clear_deadline:
            verdicts = []
            for p in ports:
                try:
                    verdicts.append(
                        rpc(p, "status")["result"].get("health", {}).get("verdict")
                    )
                except Exception:
                    verdicts.append(None)
            if all(v == "ok" for v in verdicts):
                health_recovered = True
                break
            time.sleep(0.5)

        lat = load["commit_latency_under_load_ms"]
        result = {
            "metric": "load_smoke",
            "tx_ingress_sustained_tps": load["tx_ingress_sustained_tps"],
            "commit_latency_under_load_ms": lat.get("p90", -1.0),
            "commit_latency_percentiles_ms": lat,
            "offered_tps": load["offered_tps"],
            "throttled": load["throttled"],
            "rejected": load["rejected"],
            "transport_errors": load["transport_errors"],
            "retry_after_seen": load["retry_after_seen"],
            "commits_under_load": load["commits_under_load"],
            "idle_commits_per_sec": round(idle_cps, 2),
            "recovery_commits_per_sec": round(recover_cps, 2),
            "health_degraded_under_load": sorted(health_seen["degraded"]),
            "health_recovered": health_recovered,
            "heights": [height_of(p) for p in ports],
            **checker.summary(),
        }

        failures = []
        if checker.violations:
            failures.append(f"invariant violations: {checker.violations}")
        if load["tx_ingress_sustained_tps"] <= 0:
            failures.append("no txs accepted under load")
        if load["offered_tps"] < 2 * load["tx_ingress_sustained_tps"]:
            failures.append(
                f"firehose never saturated admission: offered "
                f"{load['offered_tps']}/s < 2x accepted "
                f"{load['tx_ingress_sustained_tps']}/s"
            )
        if load["throttled"] <= 0:
            failures.append("no explicit overload rejections observed")
        if load["retry_after_seen"] <= 0:
            failures.append("overload errors carried no retry_after hint")
        silent = load["transport_errors"] / max(1, load["offered"])
        if silent > 0.05:
            failures.append(
                f"{silent:.1%} of offered txs vanished into transport errors "
                "(silent drops)"
            )
        if load["commits_under_load"] < 2 or tip_after_load <= 1:
            failures.append("consensus stalled under the firehose")
        if lat.get("p90", -1.0) < 0 or lat["p90"] > args.latency_bound:
            failures.append(
                f"commit latency under load p90 {lat.get('p90')} ms exceeds "
                f"{args.latency_bound} ms"
            )
        if recover_cps < idle_cps / 2:
            failures.append(
                f"post-firehose commit rate {recover_cps:.2f}/s did not recover "
                f"to within 2x idle ({idle_cps:.2f}/s)"
            )
        if len(checker.agreed_heights()) < 3:
            failures.append("too few heights cross-checked for agreement")
        if not health_seen["block_present"]:
            failures.append("/status never carried a health block (watchdog off?)")
        if not health_seen["degraded"]:
            failures.append(
                "no node self-reported degradation during the firehose "
                "(the watchdog missed sustained saturation)"
            )
        if not health_recovered:
            failures.append(
                "health verdict did not return to ok after the firehose"
            )
        if failures:
            print("LOAD SMOKE FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
        else:
            print(
                f"load smoke ok: {load['tx_ingress_sustained_tps']} tx/s "
                f"sustained under a {load['offered_tps']} tx/s firehose, "
                f"p90 commit interval {lat['p90']} ms, agreement over "
                f"{len(checker.agreed_heights())} heights, recovery "
                f"{recover_cps:.2f}/s vs idle {idle_cps:.2f}/s"
            )
            ok = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
