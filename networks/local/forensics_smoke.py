#!/usr/bin/env python
"""Forensics smoke: crash-persistent black-box + live self-diagnosis
against a real 4-validator localnet — the `make forensics-smoke`
acceptance rig for the flight spool, the `debug dump` bundles and the
health watchdog.

Two acts:

  1. WATCHDOG, live.  After a quiet phase in which every node's /health
     must be alarm-free (zero false alarms), a 0,1|2,3 partition is
     staged through the chaos link layer: some node must raise the
     consensus_stall alarm while the cut holds
     (`health_detect_latency_ms` — injected fault to self-reported
     alarm), and after heal every node must CLEAR it within the recovery
     bound (`health_clear_ms`).

  2. FORENSICS, dead.  node3 is SIGKILLed mid-run — no signal handler,
     no atexit, nothing runs.  `tendermint_tpu debug dump --offline`
     then builds a bundle purely from its home directory, and the
     rig asserts the bundle's spool replay reconstructs a COMPLETE
     propose→prevote→precommit→commit span chain for every interior
     pre-crash height (`crash_bundle_completeness` = complete/interior,
     must be 1.0), that the watchdog's own health.alarm/health.clear
     events survived the crash inside the spool, and that the dead
     node's spool merges with a live node's RPC dump into one aligned
     causal timeline (tracemerge on a corpse).

With --json the last stdout line carries `crash_bundle_completeness` and
`health_detect_latency_ms` — the numbers bench.py reports.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tarfile
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from tendermint_tpu.config import load_config, save_config  # noqa: E402
from tendermint_tpu.libs import tracemerge  # noqa: E402


def rpc(port: int, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def rpc_call(port: int, method: str, **params):
    qs = urllib.parse.urlencode({k: str(v) for k, v in params.items()})
    return rpc(port, f"{method}?{qs}" if qs else method)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def health_of(port: int):
    try:
        return rpc(port, "health")["result"]
    except Exception:
        return None


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-forensics")
    ap.add_argument("--base-port", type=int, default=32656)
    ap.add_argument("--quiet", type=float, default=2.5,
                    help="seconds of alarm-free running required before faults")
    ap.add_argument("--detect-bound", type=float, default=20.0,
                    help="max seconds from partition to the stall alarm")
    ap.add_argument("--partition-hold", type=float, default=6.0,
                    help="minimum partition duration: every node's own "
                    "stall threshold must elapse so every spool carries "
                    "the health.alarm event")
    ap.add_argument("--recovery-bound", type=float, default=60.0,
                    help="max seconds from heal to commits resuming")
    ap.add_argument("--clear-bound", type=float, default=10.0,
                    help="max seconds from commits resuming to every node "
                    "clearing the stall alarm (watchdog tick latency, not "
                    "net re-mesh time)")
    ap.add_argument("--post-heal", type=float, default=4.0,
                    help="clean running time before the SIGKILL")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build, "--base-port", str(args.base_port),
         "--fast", "--db-backend", "sqlite", "--chaos"],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [args.base_port + 10 * i + 1 for i in range(4)]

    # arm the forensics layer: the spool is opt-in, the rig is its proof
    for home in homes:
        path = os.path.join(home, "config", "config.toml")
        cfg = load_config(path, home=home)
        cfg.instrumentation.flight_spool = True
        cfg.instrumentation.flight_spool_flush_interval = 0.2
        cfg.instrumentation.flight_spool_size_limit = 16 * 1024 * 1024
        cfg.instrumentation.watchdog_interval = 0.25
        cfg.instrumentation.watchdog_stall_seconds = 2.5
        save_config(cfg, path)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [spawn(h, env) for h in homes]

    result = {}
    failures = []
    ok = False
    try:
        deadline = time.time() + 120.0
        while time.time() < deadline:
            hs = [height_of(p) for p in ports]
            if all(h is not None and h >= 1 for h in hs):
                break
            if any(p.poll() is not None for p in procs):
                print("a node died during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"startup timeout: heights {[height_of(p) for p in ports]}",
                  file=sys.stderr)
            return 1
        node_ids = [rpc(p, "status")["result"]["node_info"]["id"] for p in ports]
        print(f"localnet ready, heights {[height_of(p) for p in ports]}")

        # -- act 1: watchdog against an injected partition ------------------
        quiet_alarms = set()
        t_end = time.time() + args.quiet
        while time.time() < t_end:
            for i, p in enumerate(ports):
                h = health_of(p)
                if h is None:
                    continue
                quiet_alarms.update(f"node{i}:{a}" for a in h.get("alarms", {}))
            time.sleep(0.25)
        if quiet_alarms:
            failures.append(f"false alarms during the quiet phase: {sorted(quiet_alarms)}")

        print("staging 0,1|2,3 partition")
        for a, b in [(0, 2), (0, 3), (1, 2), (1, 3)]:
            rpc_call(ports[a], "unsafe_chaos_link", peer_id=node_ids[b], drop=1.0)
            rpc_call(ports[b], "unsafe_chaos_link", peer_id=node_ids[a], drop=1.0)
        t_part = time.time()
        detect_ms = None
        while time.time() < t_part + args.detect_bound:
            for i, p in enumerate(ports):
                h = health_of(p)
                if h is not None and "consensus_stall" in h.get("alarms", {}):
                    detect_ms = round((time.time() - t_part) * 1000, 1)
                    print(f"  node{i} raised consensus_stall after {detect_ms:.0f} ms")
                    break
            if detect_ms is not None:
                break
            time.sleep(0.2)
        if detect_ms is None:
            failures.append(
                f"no consensus_stall alarm within {args.detect_bound}s of the partition"
            )

        # hold the cut until EVERY node's own stall threshold has elapsed
        # (each node must raise — and later clear — its own alarm, so the
        # health.alarm/clear events land in every spool)
        time.sleep(max(0.0, t_part + args.partition_hold - time.time()))
        alarmed = [
            i for i, p in enumerate(ports)
            if (health_of(p) or {}).get("alarms", {}).get("consensus_stall")
        ]
        if len(alarmed) < 4:
            failures.append(
                f"only nodes {alarmed} raised consensus_stall while the cut held"
            )

        print("healing")
        for p in ports:
            rpc_call(p, "unsafe_chaos_heal")
        t_heal = time.time()
        # phase 1: commits resume (net recovery — re-dial + round
        # reconvergence; the chaos engine's number, bounded loosely)
        base_tip = max(
            (h for h in (height_of(p) for p in ports) if h is not None), default=0
        )
        recovery_ms = None
        while time.time() < t_heal + args.recovery_bound:
            tips = [h for h in (height_of(p) for p in ports) if h is not None]
            if tips and max(tips) > base_tip:
                recovery_ms = round((time.time() - t_heal) * 1000, 1)
                print(f"  commits resumed {recovery_ms:.0f} ms after heal")
                break
            time.sleep(0.2)
        if recovery_ms is None:
            failures.append(
                f"commits did not resume within {args.recovery_bound}s of heal"
            )
        # phase 2: the watchdogs NOTICE the recovery — all-clear within a
        # tick-latency bound of commits resuming (this PR's number)
        t_rec = time.time()
        clear_ms = None
        while time.time() < t_rec + args.clear_bound:
            states = [health_of(p) for p in ports]
            if all(
                h is not None and "consensus_stall" not in h.get("alarms", {})
                for h in states
            ):
                clear_ms = round((time.time() - t_heal) * 1000, 1)
                print(f"  stall alarm clear on every node "
                      f"{round((time.time() - t_rec) * 1000):d} ms after recovery")
                break
            time.sleep(0.2)
        if clear_ms is None:
            failures.append(
                f"stall alarm did not clear on every node within "
                f"{args.clear_bound}s of commits resuming"
            )

        time.sleep(args.post_heal)  # clean post-heal heights for the spool

        # -- act 2: SIGKILL + offline bundle --------------------------------
        victim_tip = height_of(ports[3])
        print(f"SIGKILLing node3 at height {victim_tip}")
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(10)
        time.sleep(0.5)

        dump_dir = os.path.join(build, "bundles")
        run = subprocess.run(
            [sys.executable, "-m", "tendermint_tpu.cli", "--home", homes[3],
             "debug", "dump", "--offline", "--output", dump_dir],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        print(run.stdout.strip())
        if run.returncode != 0:
            failures.append(f"offline debug dump failed: {run.stderr[-500:]}")
            raise SystemExit
        bundles = sorted(
            os.path.join(dump_dir, f) for f in os.listdir(dump_dir)
            if f.endswith(".tar.gz")
        )
        if not bundles:
            failures.append("debug dump wrote no bundle")
            raise SystemExit

        sections = {}
        with tarfile.open(bundles[-1]) as tar:
            for member in tar.getmembers():
                name = os.path.basename(member.name)
                fh = tar.extractfile(member)
                if fh is not None:
                    sections[name] = fh.read()
        need = {"manifest.json", "config.toml", "spool.json", "span_report.json"}
        missing = need - set(sections)
        if missing:
            failures.append(f"bundle missing sections: {sorted(missing)}")
            raise SystemExit

        spool_dump = json.loads(sections["spool.json"])
        rep = json.loads(sections["span_report.json"])
        interior = rep["interior"]
        complete = len(rep["complete"])
        completeness = round(complete / interior, 3) if interior else 0.0
        print(
            f"offline bundle: {len(spool_dump['events'])} spool events, "
            f"{complete}/{interior} interior pre-crash heights with complete "
            f"span chains (bad={rep['bad']}, truncated={len(rep['truncated'])})"
        )
        if interior < 3:
            failures.append(f"too few interior pre-crash heights recorded ({interior})")
        if rep["bad"]:
            failures.append(f"broken span chains in the crash spool: {rep['bad']}")
        if complete != interior:
            failures.append(
                f"crash bundle incomplete: {complete}/{interior} heights "
                f"(truncated {rep['truncated']})"
            )
        kinds = {ev.get("kind") for ev in spool_dump["events"]}
        if "health.alarm" not in kinds or "health.clear" not in kinds:
            failures.append(
                "the watchdog's health.alarm/health.clear self-diagnosis did "
                f"not survive the crash in the spool (kinds seen: {len(kinds)})"
            )

        # the critical transition must have auto-captured a bundle too
        auto_dir = os.path.join(homes[3], "data", "forensics")
        autodumps = (
            [f for f in os.listdir(auto_dir) if f.endswith(".tar.gz")]
            if os.path.isdir(auto_dir) else []
        )
        if not autodumps:
            failures.append("no auto-bundle written on the critical transition")

        # dead-node causal merge: the corpse's spool + a live node's RPC
        # dump onto one timeline with agreeing hashes
        spool_path = os.path.join(homes[3], "data", "flight.spool")
        dead = tracemerge.load_dump(spool_path, name="node3-dead")
        live = rpc(ports[0], "dump_flight_recorder")["result"]
        live["node"] = "node0"
        merged = tracemerge.merge([dead, live])
        shared = [
            h for h, e in merged["heights"].items()
            if "node3-dead" in e["nodes"] and "node0" in e["nodes"]
        ]
        if len(shared) < 3:
            failures.append(
                f"dead-node merge aligned only {len(shared)} shared heights"
            )
        if merged["hash_mismatch_heights"]:
            failures.append(
                f"dead-node merge hash mismatch at {merged['hash_mismatch_heights']}"
            )
        print(
            f"dead-node causal merge: {len(shared)} shared heights aligned, "
            f"commit skew p90 {merged['commit_skew_ms_p90']} ms"
        )

        result = {
            "metric": "forensics_smoke",
            "crash_bundle_completeness": completeness,
            "health_detect_latency_ms": detect_ms if detect_ms is not None else -1.0,
            "health_clear_ms": clear_ms if clear_ms is not None else -1.0,
            "heal_recovery_ms": recovery_ms if recovery_ms is not None else -1.0,
            "interior_precrash_heights": interior,
            "spool_events": len(spool_dump["events"]),
            "spool_dropped": spool_dump.get("dropped", 0),
            "bundle_sections": len(sections),
            "autodumps": len(autodumps),
            "merged_shared_heights": len(shared),
            "victim_tip": victim_tip,
            "heights": [height_of(p) for p in ports[:3]],
        }
    except SystemExit:
        pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()

    if failures:
        print("FORENSICS SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
    elif result:
        print(
            f"forensics smoke ok: crash bundle complete "
            f"({result['interior_precrash_heights']} pre-crash heights from "
            f"{result['spool_events']} spooled events), stall alarm in "
            f"{result['health_detect_latency_ms']:.0f} ms, clear in "
            f"{result['health_clear_ms']:.0f} ms, {result['autodumps']} "
            f"auto-bundle(s), dead-node merge aligned"
        )
        ok = True
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok and not failures else 1


if __name__ == "__main__":
    sys.exit(main())
