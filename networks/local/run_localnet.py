#!/usr/bin/env python
"""Run a generated testnet as real OS processes (no docker needed).

Usage:
    python -m tendermint_tpu.cli testnet --validators 4 --output ./build [--fast]
    python networks/local/run_localnet.py ./build [--duration 30] [--json]

Spawns one `tendermint_tpu node` process per node directory (RPC/P2P ports
are read from each node's config.toml — no port arithmetic, so generators
can use any free ports), waits until EVERY node's RPC answers with height
>= 1 (readiness gate: per-process JAX import + XLA warmup takes seconds
and must not eat into the measurement window), then measures committed
blocks per second over --duration seconds of wall clock.

Exit code 0 iff every node committed at least 3 blocks and all heads agree
within 2 heights.  With --json, the last stdout line is a JSON object:
{"commits_per_sec", "blocks", "measure_s", "startup_s", "heights"} —
the e2e_commits_per_sec_4val_procs number bench.py reports (BASELINE
config #1 measured from real multi-process nodes, not one shared event
loop).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

try:
    import tomllib
except ImportError:  # Python < 3.11
    import tomli as tomllib

# the script lives in networks/local/; the package at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
from tendermint_tpu.libs import tracing  # noqa: E402


def rpc(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=2) as r:
        return json.load(r)


def rpc_port_of(home: str) -> int:
    with open(os.path.join(home, "config", "config.toml"), "rb") as f:
        laddr = tomllib.load(f)["rpc"]["laddr"]
    # "tcp://127.0.0.1:26657" or "127.0.0.1:26657"
    return int(laddr.rsplit(":", 1)[1])


def dump_recorder(port: int) -> dict:
    """One node's full dump_flight_recorder snapshot (events + dropped
    count + the monotonic→wall anchor trace-net alignment needs)."""
    return rpc(port, "dump_flight_recorder")["result"]


def trace_check(rpc_ports) -> bool:
    """Every node must show complete propose→commit span chains for its
    interior recorded heights.  A busy ring that wrapped mid-chain reports
    prefix-truncated heights — honest, not fatal (hard-failing there made
    the check useless exactly on the loaded nets it is for); only a
    mid-chain hole fails.  This is what `make trace-smoke` asserts."""
    ok = True
    for port in rpc_ports:
        try:
            snap = dump_recorder(port)
        except Exception as e:
            print(f"trace check: node on :{port} unreachable: {e}", file=sys.stderr)
            ok = False
            continue
        rep = tracing.span_report(snap["events"], dropped=snap.get("dropped", 0))
        if rep["interior"] < 3 or rep["bad"] or not rep["complete"]:
            print(
                f"trace check FAILED on :{port}: {rep['interior']} interior heights, "
                f"complete={len(rep['complete'])} truncated={len(rep['truncated'])} "
                f"broken chains: {rep['bad']}",
                file=sys.stderr,
            )
            ok = False
        else:
            msg = f"trace check ok on :{port}: {len(rep['complete'])} complete span chains"
            if rep["truncated"]:
                msg += f" ({len(rep['truncated'])} truncated by ring wrap)"
            print(msg)
    return ok


def poll_heights(rpc_ports) -> list:
    heights = []
    for port in rpc_ports:
        try:
            heights.append(
                int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
            )
        except Exception:
            heights.append(-1)
    return heights


def poll_ready(rpc_ports) -> list:
    """Per-node readiness: height >= 1 AND the node reports sync phase
    `caught_up` (`/status` sync_info.sync_phase — a node mid-statesync or
    mid-fastsync serves RPC long before it can keep up with the net, so
    height alone is a premature gate).  Missing key falls back to the old
    height-only check."""
    ready = []
    for port in rpc_ports:
        try:
            si = rpc(port, "status")["result"]["sync_info"]
            ok = int(si["latest_block_height"]) >= 1
            if "sync_phase" in si:
                ok = ok and si["sync_phase"] == "caught_up"
            ready.append(ok)
        except Exception:
            ready.append(False)
    return ready


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("build_dir")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="measurement window AFTER all nodes are ready")
    ap.add_argument("--startup-timeout", type=float, default=90.0,
                    help="max wait for every node's RPC to report height >= 1")
    ap.add_argument("--json", action="store_true",
                    help="print a JSON result line (commits/sec) at the end")
    ap.add_argument("--trace-check", action="store_true",
                    help="fail unless every node's flight recorder shows a complete "
                    "propose→commit span chain for every interior block")
    ap.add_argument("--dump-recorders", default="",
                    help="directory to write every node's recorder dump "
                    "(one JSON per node — `tendermint_tpu trace-net` input)")
    ap.add_argument("--trace-net", action="store_true",
                    help="merge every node's dump into one causal timeline and "
                    "fail unless it is complete, aligned, and carries nonzero "
                    "loop attribution for every interior block (trace-net-smoke)")
    args = ap.parse_args()

    homes = sorted(
        os.path.join(args.build_dir, d)
        for d in os.listdir(args.build_dir)
        if d.startswith("node")
    )
    if not homes:
        print(f"no node*/ directories under {args.build_dir}", file=sys.stderr)
        return 2
    rpc_ports = [rpc_port_of(home) for home in homes]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # all nodes compile identical XLA kernels — share one persistent cache
    # so only the first process (ever) pays each compile
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        for home in homes
    ]
    print(f"spawned {len(procs)} nodes; waiting for all RPCs to reach height 1")
    ok = False
    result = {}
    try:
        # readiness gate: the duration clock starts only once every node is
        # serving RPC and has committed its first block
        t_start = time.time()
        ready_deadline = t_start + args.startup_timeout
        while time.time() < ready_deadline:
            if all(poll_ready(rpc_ports)):
                heights = poll_heights(rpc_ports)
                if min(heights) >= 1:
                    break
            if any(p.poll() is not None for p in procs):
                print("a node process exited during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"startup timeout: heights {poll_heights(rpc_ports)}", file=sys.stderr)
            return 1
        startup_s = time.time() - t_start

        # the gate's heights are already validated (all >= 1); a fresh poll
        # could transiently fail to -1 under load and corrupt the baseline
        start_heights = heights
        t0 = time.time()
        deadline = t0 + args.duration
        while time.time() < deadline:
            time.sleep(min(2.0, max(0.1, deadline - time.time())))
            heights = poll_heights(rpc_ports)
            print("heights:", heights)
        # retry any RPC that failed on the final poll — a single timed-out
        # status call must not turn the headline commits/sec negative
        for _ in range(5):
            if min(heights) >= 0:
                break
            time.sleep(0.5)
            retried = poll_heights(rpc_ports)
            heights = [max(a, b) for a, b in zip(heights, retried)]
        measure_s = time.time() - t0
        blocks = min(heights) - min(start_heights)
        result = {
            "commits_per_sec": round(blocks / measure_s, 2),
            "blocks": blocks,
            "measure_s": round(measure_s, 2),
            "startup_s": round(startup_s, 2),
            "heights": heights,
        }
        # per-block ms timeline from node0's flight recorder — the same
        # event stream dump_flight_recorder serves; bench.py sources its
        # e2e_4val_breakdown from this instead of ad-hoc timers
        try:
            result["recorder"] = tracing.block_breakdown(dump_recorder(rpc_ports[0])["events"])
        except Exception as e:
            print(f"flight recorder dump failed: {e}", file=sys.stderr)
        if min(heights) >= 3 and max(heights) - min(heights) <= 2:
            print("localnet healthy: all nodes committing in lock-step")
            ok = True
        if args.trace_check and not trace_check(rpc_ports):
            ok = False
        if args.dump_recorders or args.trace_net:
            try:
                snaps = []
                for i, port in enumerate(rpc_ports):
                    snap = dump_recorder(port)
                    # per-node files / timeline rows keyed by the home dir
                    # name, not the moniker (which operators may not vary)
                    snap["node"] = os.path.basename(homes[i])
                    snaps.append(snap)
            except Exception as e:
                print(f"recorder dump failed: {e}", file=sys.stderr)
                if args.trace_net:
                    ok = False
                snaps = []
            if snaps and args.dump_recorders:
                os.makedirs(args.dump_recorders, exist_ok=True)
                for snap in snaps:
                    path = os.path.join(args.dump_recorders, f"{snap['node']}.json")
                    with open(path, "w") as fh:
                        json.dump(snap, fh)
                print(f"wrote {len(snaps)} recorder dumps to {args.dump_recorders}")
            if snaps and args.trace_net:
                # merged causal timeline across every process — each node
                # is a separate interpreter here, so the per-node loop
                # attribution is TRUE per-node (unlike the in-proc rigs)
                from tendermint_tpu.libs import tracemerge

                merged = tracemerge.merge(snaps)
                failures = tracemerge.check(snaps, merged)
                result["trace_net"] = {
                    "heights": len(merged["heights"]),
                    "offsets_ms": merged["offsets_ms"],
                    "commit_skew_ms_p50": merged["commit_skew_ms_p50"],
                    "commit_skew_ms_p90": merged["commit_skew_ms_p90"],
                    "coverage_ms_p90": merged["coverage_ms_p90"],
                    "attribution": {
                        s["node"]: tracemerge.median_attribution(
                            tracemerge.attribution_by_height(s)
                        )
                        for s in snaps
                    },
                    "failures": failures,
                }
                slow = tracemerge.slowest_height(merged)
                if slow is not None:
                    print(f"slowest block (height {slow}) on the merged timeline:")
                    print(tracemerge.format_timeline(merged, [slow]))
                print(tracemerge.format_attribution(snaps))
                if failures:
                    print("trace-net check FAILED:", file=sys.stderr)
                    for f in failures:
                        print(f"  - {f}", file=sys.stderr)
                    ok = False
                else:
                    print(
                        f"trace-net check ok: {len(merged['heights'])} heights "
                        f"aligned across {len(snaps)} nodes"
                    )
    except KeyboardInterrupt:
        pass
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
