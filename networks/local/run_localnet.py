#!/usr/bin/env python
"""Run a generated testnet as real OS processes (no docker needed).

Usage:
    python -m tendermint_tpu.cli testnet --validators 4 --output ./build
    python networks/local/run_localnet.py ./build [--duration 30]

Spawns one `tendermint_tpu node` process per node directory, polls every
node's RPC for height, prints progress, and tears everything down on
Ctrl-C or after --duration seconds.  Exit code 0 iff every node committed
at least 3 blocks and all heads agree within 2 heights.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request


def rpc(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=2) as r:
        return json.load(r)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("build_dir")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--base-port", type=int, default=26656)
    args = ap.parse_args()

    homes = sorted(
        os.path.join(args.build_dir, d)
        for d in os.listdir(args.build_dir)
        if d.startswith("node")
    )
    if not homes:
        print(f"no node*/ directories under {args.build_dir}", file=sys.stderr)
        return 2
    rpc_ports = [args.base_port + 10 * i + 1 for i in range(len(homes))]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        for home in homes
    ]
    print(f"spawned {len(procs)} nodes; polling for {args.duration:.0f}s")
    ok = False
    try:
        deadline = time.time() + args.duration
        while time.time() < deadline:
            time.sleep(2)
            heights = []
            for port in rpc_ports:
                try:
                    heights.append(
                        int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
                    )
                except Exception:
                    heights.append(-1)
            print("heights:", heights)
            if min(heights) >= 3 and max(heights) - min(heights) <= 2:
                print("localnet healthy: all nodes committing in lock-step")
                ok = True
                break
    except KeyboardInterrupt:
        pass
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
