#!/usr/bin/env python
"""Rotation smoke: dynamic validator sets driven end-to-end through the
staking app — the `make rotation-smoke` acceptance rig for PR "dynamic
validator sets".

A 7-node in-process net starts with 4 genesis validators (distinct powers)
running the staking ABCI app with epoch rotation enabled, then lives
through every set transition the subsystem promises, all via REAL signed
stake txs (no backdoor set surgery):

  1. growth     — three non-validators bond in (one directly via the rig,
                  two through the scenario DSL's new `valset` clauses);
                  `valset_update_latency_ms` is measured tx-submit →
                  set-effective.
  2. chaos      — one joiner is a configured TwinSigner: it starts
                  equivocating the moment it becomes a validator (a twin
                  ACROSS a set change), halts reference-correctly, and its
                  DuplicateVoteEvidence must land in a committed block.  A
                  partition across the set change + heal rides the same
                  scenario.
  3. epochs     — the staking app's epoch barrel-shift must change the
                  power assignment with ZERO client traffic.
  4. migration  — after the halted twin is voted out (stake tx signed with
                  its owner key, submitted through a live node), every
                  remaining validator live-rotates ed25519 → BLS12-381.
                  Aggregation must ENGAGE (stored commits become ONE
                  aggregate signature + bitmap; `bls_migration_height_gap`
                  = uniformity → first AggregateCommit) and then DISENGAGE
                  when one validator rotates back to ed25519.
  5. bootstrap  — a fresh node fastsyncs from genesis ACROSS the rotated/
                  mixed/aggregated history (catchup commits authenticated
                  against historical sets), and a lite2 client bisects from
                  a height-2 trust root to the tip over every set change
                  (`lite2_skip_across_rotation_ok`).
  6. judgement  — the chaos invariant checker (agreement, no height
                  regression; twin liveness-exempt) must report ZERO
                  violations, and the engine's set-rebuild pipeline must
                  have provably fired (`valset.update` +
                  `verify.table_rebuild` recorder events).

Engine note: the verify engine is ON (`tpu.enabled`); on a CPU-only host
`min_device_batch` routes batches to the threaded C host tier exactly like
scale_smoke, which keeps TableCache alive so set changes exercise the
rebuild path cheaply.

With --json the last stdout line carries `valset_update_latency_ms`,
`bls_migration_height_gap` and `lite2_skip_across_rotation_ok` — the
numbers bench.py's bench_rotation reports.
"""

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

# node roles (indices into the rig's node list)
GENESIS_VALS = [0, 1, 2, 3]
JOINER_A = 5          # bonds in directly via the rig (latency measurement)
JOINER_B = 6          # bonds in via the scenario DSL
TWIN = 4              # configured double-signer; bonds in via the DSL
FRESH = 7             # fastsync bootstrapper over the rotated history
GENESIS_POWERS = [10, 20, 30, 40]


def _node_cfg(tmp: str, i: int, args, cpu_only: bool):
    from tendermint_tpu.config import test_config as make_test_cfg

    cfg = make_test_cfg(os.path.join(tmp, f"n{i}"))
    cfg.rpc.laddr = ""
    cfg.base.db_backend = "memdb"
    cfg.base.proxy_app = "staking"
    cfg.p2p.laddr = "127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.p2p.dial_timeout = 20.0
    cfg.p2p.max_num_inbound_peers = 16
    cfg.p2p.max_num_outbound_peers = 16
    # verify engine ON — set changes must hit the TableCache rebuild path.
    # CPU-only hosts route batches to the threaded C host tier via the
    # engine's own min_device_batch mechanism (the scale_smoke idiom).
    cfg.tpu.enabled = True
    if cpu_only:
        cfg.tpu.min_device_batch = 1 << 30
    cfg.chaos.enabled = True
    cfg.chaos.seed = args.seed
    if i == TWIN:
        cfg.chaos.twin = True
    # pace blocks at a steady few per second: heights must advance (epoch
    # boundaries, evidence inclusion) but the run spans minutes of wall
    # time and an unpaced empty-block net would pile up thousands of
    # heights for the fastsync/lite2 phases to chew through
    cfg.consensus.timeout_commit = args.block_pace
    cfg.consensus.skip_timeout_commit = False
    cfg.base.fast_sync = True  # coordinated launch gate (see build_net)
    cfg.instrumentation.watchdog = False
    # table rebuilds only fire while the set is all-ed25519, i.e. in the
    # first half of the run; the BLS/fastsync/lite2 phases emit enough
    # gossip+verify events afterwards to cycle the default 8192-slot ring
    # and evict them before the final judgement count — keep the whole run
    cfg.instrumentation.flight_recorder_size = 1 << 17
    return cfg


async def build_net(tmp: str, args, cpu_only: bool):
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV, RotatingPV
    from tendermint_tpu.types.params import BlockParams, ConsensusParams
    from tendermint_tpu.crypto.bls.keys import BlsPrivKey

    # Every migratable node holds a RotatingPV: candidate 0 is its ed25519
    # identity (the pre-migration signer AND the stake-tx owner key),
    # candidate 1 its BLS12-381 one.  The twin keeps a plain MockPV —
    # TwinSigner wraps a single raw key — and therefore never migrates.
    pvs = []
    for i in range(7):
        if i == TWIN:
            pvs.append(MockPV())
        else:
            pvs.append(RotatingPV(MockPV(), MockPV(BlsPrivKey.generate())))
    # sort the genesis validators by address so node index order matches
    # validator set order for the first 4 (log readability only)
    genesis_pvs = sorted(pvs[:4], key=lambda pv: pv.address())
    pvs[:4] = genesis_pvs

    gen = GenesisDoc(
        chain_id="rotation-smoke",
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pv.address(), pv.get_pub_key(), power)
            for pv, power in zip(genesis_pvs, GENESIS_POWERS)
        ],
        consensus_params=ConsensusParams(block=BlockParams(time_iota_ms=1)),
        app_state={"staking": {"epoch_length": args.epoch}},
    )

    nodes = [
        Node(_node_cfg(tmp, i, args, cpu_only), gen, priv_validator=pvs[i], db_backend="memdb")
        for i in range(7)
    ]

    # coordinated launch behind the fastsync gate while the mesh forms
    from tendermint_tpu.fastsync import reactor as fs_reactor

    orig_interval = fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL
    fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL = 3600.0
    t0 = time.perf_counter()
    try:
        for node in nodes:
            await node.start()
        for attempt in range(4):
            # dial one direction only (i < j): simultaneous mutual dials
            # collide as duplicate connections and both get dropped
            dials = [
                (i, f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}")
                for i in range(7)
                for j in range(i + 1, 7)
                if nodes[j].node_key.id not in nodes[i].switch.peers
            ]
            if not dials:
                break
            await asyncio.gather(
                *(nodes[i].switch.dial_peer(addr) for i, addr in dials),
                return_exceptions=True,
            )
            await asyncio.sleep(0.5)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(node.switch.num_peers() >= 6 for node in nodes):
                break
            await asyncio.sleep(0.2)
        else:
            raise RuntimeError(
                f"mesh never converged: {[n.switch.num_peers() for n in nodes]}"
            )
    finally:
        fs_reactor.SWITCH_TO_CONSENSUS_INTERVAL = orig_interval
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if all(n.consensus is not None and n.consensus.is_running for n in nodes):
            break
        await asyncio.sleep(0.2)
    else:
        raise RuntimeError("nodes never switched fastsync→consensus")
    return nodes, gen, time.perf_counter() - t0


def _ed_addr(pv) -> bytes:
    """The node's ed25519 identity address (RotatingPV candidate 0 /
    MockPV), independent of which key is currently active."""
    cand = getattr(pv, "candidates", None)
    return (cand[0] if cand else pv).get_pub_key().address()


def _bls_addr(pv) -> bytes:
    for cand in getattr(pv, "candidates", []):
        if getattr(cand.get_pub_key(), "TYPE", "") == "tendermint/PubKeyBLS12381":
            return cand.get_pub_key().address()
    raise RuntimeError("node has no BLS candidate key")


def _val_set(node):
    """The CURRENT consensus validator set from the canonical store."""
    return node.state_store.load().validators


def _powers_by_addr(vset) -> dict:
    return {v.address.hex(): v.voting_power for v in vset.validators}


async def _wait_for(predicate, budget: float, what: str, tick: float = 0.1):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(tick)
    raise TimeoutError(f"timed out after {budget:.0f}s waiting for {what}")


def _tip(nodes) -> int:
    return max(n.block_store.height() for n in nodes)


async def _mesh_keeper(nodes, interval: float = 2.0):
    """Redial any dropped links (one direction, i < j).  pex is off, so a
    connection killed by a transient error (overload drop, decode error
    during the fastsync→consensus switch race) never heals on its own and
    strands a follower at height 0.  Partitions are message-drop policies
    on LIVE links keyed by peer id, so redialing never bypasses them."""
    while True:
        await asyncio.sleep(interval)
        dials = []
        for i, a in enumerate(nodes):
            if not a.is_running:
                continue
            for j in range(i + 1, len(nodes)):
                b = nodes[j]
                if not b.is_running or b.node_key.id in a.switch.peers:
                    continue
                dials.append(
                    a.switch.dial_peer(
                        f"{b.node_key.id}@{b.switch.transport.listen_addr}"
                    )
                )
        if dials:
            await asyncio.gather(*dials, return_exceptions=True)


def recorder_counts(nodes) -> dict:
    valset_updates = rebuilds = rebuild_ok = 0
    for node in nodes:
        for e in node.flight_recorder.events():
            if e["kind"] == "valset.update":
                valset_updates += 1
            elif e["kind"] == "verify.table_rebuild":
                rebuilds += 1
                rebuild_ok += 1 if e.get("ok") else 0
    return {
        "valset_update_events": valset_updates,
        "table_rebuild_events": rebuilds,
        "table_rebuild_ok_events": rebuild_ok,
    }


async def run(args) -> dict:
    import jax

    from tendermint_tpu.chaos import InProcRig, InvariantChecker, Scenario, ScenarioRunner
    from tendermint_tpu.chaos.checker import scan_committed_evidence
    from tendermint_tpu.types import Commit
    from tendermint_tpu.types.agg_commit import AggregateCommit
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence

    cpu_only = all(d.platform == "cpu" for d in jax.devices())
    result = {
        "metric": "rotation_smoke",
        "engine_device_path": not cpu_only,
        "epoch_length": args.epoch,
        "failures": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        nodes, gen, startup_s = await build_net(tmp, args, cpu_only)
        result["startup_s"] = round(startup_s, 1)
        pvs = [n.priv_validator for n in nodes]
        # the twin's privval is wrapped in TwinSigner by Node; its identity
        # is still the inner ed25519 key
        ed_addrs = [_ed_addr(pv) for pv in pvs]
        print(
            f"net up: 4 genesis validators + 3 followers, startup {startup_s:.1f}s, "
            f"engine {'device' if not cpu_only else 'host-tier (CPU-only box)'}",
            flush=True,
        )
        fresh_node = None
        keeper_nodes = list(nodes)
        keeper = asyncio.ensure_future(_mesh_keeper(keeper_nodes))
        try:
            # -- phase 1: base chain, then measured growth ----------------
            await _wait_for(
                lambda: min(n.block_store.height() for n in nodes) >= 3,
                args.budget, "3 base commits everywhere",
            )
            rig = InProcRig(nodes)

            t_join = time.monotonic()
            await rig.valset("join", JOINER_A, power=15)
            addr_a = ed_addrs[JOINER_A]
            await _wait_for(
                lambda: _val_set(nodes[0]).has_address(addr_a),
                args.budget, f"node {JOINER_A} joining the set",
            )
            result["valset_update_latency_ms"] = round(
                (time.monotonic() - t_join) * 1000.0, 1
            )
            print(
                f"node {JOINER_A} bonded in: set effective after "
                f"{result['valset_update_latency_ms']} ms",
                flush=True,
            )

            # -- phase 2: scenario DSL — joins + partition + twin ---------
            # The twin bonds in MID-SCENARIO (a set change), equivocates on
            # its first own prevote, and halts.  The partition spans the
            # set change; the power edit lands after heal.  25 is absent
            # from the initial power multiset {10,20,30,40,15,10,5}, so its
            # appearance proves the edit applied (the epoch barrel-shift
            # permutes powers but preserves the multiset).
            text = "\n".join(
                [
                    f"valset join {JOINER_B} power=10 @0",
                    f"valset join {TWIN} power=5 @3",
                    f"partition {TWIN},{JOINER_A}|0,1,2,3,{JOINER_B} @6",
                    "heal @12",
                    "valset power 1=25 @15",
                ]
            )
            scenario = Scenario.parse(text, seed=args.seed)
            result["scenario_fingerprint"] = scenario.fingerprint()[:16]
            await ScenarioRunner(scenario, rig).run()
            addr_b, addr_twin = ed_addrs[JOINER_B], ed_addrs[TWIN]
            await _wait_for(
                lambda: (
                    _val_set(nodes[0]).has_address(addr_b)
                    and _val_set(nodes[0]).has_address(addr_twin)
                    and 25 in _powers_by_addr(_val_set(nodes[0])).values()
                ),
                args.budget, "DSL joins + power edit effective",
            )
            result["set_size_after_growth"] = _val_set(nodes[0]).size()
            if result["set_size_after_growth"] != 7:
                result["failures"].append(
                    f"expected 7 validators after growth, got {result['set_size_after_growth']}"
                )
            print(
                f"scenario done: set grew to {result['set_size_after_growth']} "
                f"across a partition; twin armed",
                flush=True,
            )

            # -- phase 3: twin accountability -----------------------------
            def _twin_evidence():
                for h, ev in scan_committed_evidence(nodes[0].block_store, max_back=500):
                    if isinstance(ev, DuplicateVoteEvidence) and (
                        ev.vote_a.validator_address == addr_twin
                    ):
                        result["twin_evidence_height"] = h
                        return True
                return False

            try:
                await _wait_for(
                    _twin_evidence, args.budget, "twin DuplicateVoteEvidence committed"
                )
                result["twin_evidence_committed"] = True
                print(
                    f"twin evidence committed at height {result['twin_evidence_height']}",
                    flush=True,
                )
            except TimeoutError as e:
                result["twin_evidence_committed"] = False
                result["failures"].append(str(e))

            # -- phase 4: epoch barrel-shift (zero client traffic) --------
            before = _powers_by_addr(_val_set(nodes[0]))
            h_before = nodes[0].state_store.load().last_block_height
            next_epoch = ((h_before // args.epoch) + 1) * args.epoch
            await _wait_for(
                lambda: nodes[0].state_store.load().last_block_height >= next_epoch + 3,
                args.budget, f"epoch boundary {next_epoch} + 2 to pass",
            )
            after = _powers_by_addr(_val_set(nodes[0]))
            rotated = set(before) == set(after) and before != after
            result["epoch_rotation_observed"] = rotated
            if not rotated:
                result["failures"].append(
                    f"epoch boundary {next_epoch} did not rotate powers: "
                    f"{before} -> {after}"
                )
            else:
                print(f"epoch barrel-shift observed at boundary {next_epoch}", flush=True)

            # -- phase 5: vote the halted twin out ------------------------
            # stake tx signed with the twin's OWNER key (extracted through
            # TwinSigner), submitted through a live node's mempool
            await rig.valset("leave", TWIN)
            await _wait_for(
                lambda: not _val_set(nodes[0]).has_address(addr_twin),
                args.budget, "twin leaving the set",
            )
            result["set_size_after_leave"] = _val_set(nodes[0]).size()
            print("halted twin voted out of the set", flush=True)

            # snapshot recorder counts while the all-ed25519 rebuild events
            # are still in the rings; the final count takes the max so the
            # verdict survives even if later traffic cycles them out
            counts_mid = recorder_counts(nodes)

            # -- phase 6: live ed25519 -> BLS migration -------------------
            migrators = [i for i in (GENESIS_VALS + [JOINER_A, JOINER_B])]
            for i in migrators:
                await rig.valset("migrate", i, scheme="bls12381")
                bi, ei = _bls_addr(pvs[i]), ed_addrs[i]
                await _wait_for(
                    lambda: (
                        _val_set(nodes[0]).has_address(bi)
                        and not _val_set(nodes[0]).has_address(ei)
                    ),
                    args.budget, f"node {i} migrating to bls12381",
                )
                print(f"node {i} migrated to BLS (set stayed live)", flush=True)
            h_uniform = nodes[0].state_store.load().last_block_height
            result["bls_uniform_height"] = h_uniform

            # aggregation must ENGAGE: a stored commit above uniformity
            # becomes ONE aggregate signature + signer bitmap
            agg_h = {"h": 0}

            def _agg_engaged():
                bs = nodes[0].block_store
                for h in range(h_uniform, bs.height() + 1):
                    c = bs.load_block_commit(h)
                    if isinstance(c, AggregateCommit):
                        agg_h["h"] = h
                        return True
                return False

            await _wait_for(_agg_engaged, args.budget, "BLS aggregation to engage")
            result["agg_engaged_height"] = agg_h["h"]
            result["bls_migration_height_gap"] = agg_h["h"] - h_uniform
            c = nodes[0].block_store.load_block_commit(agg_h["h"])
            if len(c.agg_sig) != 96:
                result["failures"].append(
                    f"aggregate commit at {agg_h['h']} has a {len(c.agg_sig)}-byte sig"
                )
            print(
                f"aggregation ENGAGED at height {agg_h['h']} "
                f"(gap {result['bls_migration_height_gap']} from uniformity)",
                flush=True,
            )

            # ...and DISENGAGE when one validator rotates back to ed25519
            await rig.valset("migrate", 0, scheme="ed25519")
            await _wait_for(
                lambda: _val_set(nodes[0]).has_address(ed_addrs[0]),
                args.budget, "node 0 rotating back to ed25519",
            )
            h_mixed = nodes[0].state_store.load().last_block_height

            def _agg_disengaged():
                bs = nodes[0].block_store
                tip = bs.height()
                if tip < h_mixed + 3:
                    return False
                c = bs.load_block_commit(tip - 1)
                return isinstance(c, Commit) and not isinstance(c, AggregateCommit)

            await _wait_for(_agg_disengaged, args.budget, "aggregation to disengage")
            result["agg_disengaged"] = True
            print("node 0 back on ed25519: aggregation DISENGAGED (mixed set)", flush=True)

            # -- phase 7: fresh node fastsyncs the rotated history --------
            from tendermint_tpu.node import Node
            from tendermint_tpu.types import MockPV

            tip_at_join = _tip(nodes)
            cfg7 = _node_cfg(tmp, FRESH, args, cpu_only)
            cfg7.chaos.twin = False
            fresh_node = Node(cfg7, gen, priv_validator=MockPV(), db_backend="memdb")
            await fresh_node.start()
            keeper_nodes.append(fresh_node)  # mesh keeper heals its links too
            for j in range(7):
                if j == TWIN:
                    continue  # the halted twin serves nothing
                try:
                    await fresh_node.switch.dial_peer(
                        f"{nodes[j].node_key.id}@{nodes[j].switch.transport.listen_addr}"
                    )
                except Exception:
                    pass
            await _wait_for(
                lambda: fresh_node.block_store.height() >= tip_at_join,
                args.budget,
                f"fresh node fastsyncing {tip_at_join} rotated heights",
                tick=0.25,
            )
            result["fastsync_joiner_height"] = fresh_node.block_store.height()
            print(
                f"fresh node fastsynced to {result['fastsync_joiner_height']} "
                f"across every set change",
                flush=True,
            )

            # -- phase 8: lite2 bisection across every rotation -----------
            from tendermint_tpu.lite2 import BISECTION, Client, LocalProvider, TrustOptions

            root = nodes[0].block_store.load_block(2)
            lite_tip = nodes[0].block_store.height() - 1
            client = Client(
                gen.chain_id,
                TrustOptions(
                    period_ns=3600 * 1_000_000_000,
                    height=2,
                    hash=root.header.hash(),
                ),
                LocalProvider(nodes[0]),
                witnesses=[LocalProvider(nodes[1])],
                mode=BISECTION,
            )
            try:
                await client.initialize()
                sh = await client.verify_header_at_height(lite_tip, time.time_ns())
                ok = sh is not None and sh.height == lite_tip
                result["lite2_skip_across_rotation_ok"] = bool(ok)
                if not ok:
                    result["failures"].append("lite2 returned a bogus header")
                else:
                    print(
                        f"lite2 bisected height 2 -> {lite_tip} across the rotations",
                        flush=True,
                    )
            except Exception as e:
                result["lite2_skip_across_rotation_ok"] = False
                result["failures"].append(f"lite2 bisection failed: {e!r}")

            # -- phase 9: invariants + engine-rebuild proof ---------------
            checker = InvariantChecker(8, liveness_exempt=[TWIN])
            for i, node in enumerate(nodes):
                checker.observe_node(i, node)
            checker.observe_node(7, fresh_node)
            result["agreed_heights"] = len(checker.agreed_heights())
            result["max_height"] = _tip(nodes)
            if checker.violations:
                result["failures"].append(f"invariant violations: {checker.violations}")
            result["violations"] = list(checker.violations)

            counts_end = recorder_counts(nodes + [fresh_node])
            result.update(
                {k: max(counts_mid.get(k, 0), v) for k, v in counts_end.items()}
            )
            if result["valset_update_events"] == 0:
                result["failures"].append("no valset.update recorder events fired")
            if result["table_rebuild_events"] == 0:
                result["failures"].append(
                    "no verify.table_rebuild recorder events: the engine table "
                    "never rebuilt on a set change"
                )
        except (TimeoutError, RuntimeError) as e:
            result["failures"].append(str(e))
            result["heights_at_failure"] = [n.block_store.height() for n in nodes]
            result["peers_at_failure"] = [n.switch.num_peers() for n in nodes]
        finally:
            keeper.cancel()
            stopping = [n for n in nodes if n.is_running]
            if fresh_node is not None and fresh_node.is_running:
                stopping.append(fresh_node)
            await asyncio.gather(*(n.stop() for n in stopping), return_exceptions=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epoch", type=int, default=16,
                    help="staking epoch length (heights between barrel-shifts)")
    ap.add_argument("--block-pace", type=float, default=0.25,
                    help="timeout_commit pacing (seconds/block floor)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--budget", type=float, default=120.0,
                    help="per-phase wait budget (seconds)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    result = asyncio.run(run(args))
    failures = result.pop("failures", [])
    if failures:
        print("ROTATION SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
    else:
        print(
            f"rotation smoke ok: set 4→7→6 validators, valset latency "
            f"{result.get('valset_update_latency_ms', '?')} ms, epoch rotation "
            f"{'observed' if result.get('epoch_rotation_observed') else 'MISSING'}, "
            f"twin evidence at h={result.get('twin_evidence_height', '?')}, BLS "
            f"aggregation engaged at h={result.get('agg_engaged_height', '?')} "
            f"(gap {result.get('bls_migration_height_gap', '?')}) and disengaged, "
            f"fastsync to {result.get('fastsync_joiner_height', '?')}, lite2 "
            f"bisection {'ok' if result.get('lite2_skip_across_rotation_ok') else 'FAILED'}, "
            f"{result.get('valset_update_events', 0)} valset.update / "
            f"{result.get('table_rebuild_events', 0)} table_rebuild events, "
            f"0 violations"
        )
    if args.json:
        result["ok"] = not failures
        print(json.dumps(result))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
