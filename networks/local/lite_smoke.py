#!/usr/bin/env python
"""Lite smoke: the multi-tenant light-client gateway against a real
4-validator multi-process localnet — the `make lite-smoke` acceptance rig
for the liteserve subsystem.

Topology: 4 validator nodes; an ADVERSARIAL FORWARDING PROXY (in this
process) in front of node0; a liteserve gateway subprocess whose primary
is the proxy and whose witnesses are nodes 1-3.

Phases:

  fleet     >= 64 concurrent bisecting tenants (tools/loadgen.py --lite
            flavor) create sessions at a shared trust root and hammer
            verified-commit queries over random heights — the shared
            store + verification cache must absorb the fan-in
            (lite_cache_hit_ratio, lite_verify_coalesce_ratio, every
            session sustained), while the PR 5 chaos invariant checker
            scrapes the validator net underneath (agreement, no height
            regression: the gateway must cost the chain nothing)
  adversary the proxy starts serving a TWIN-SIGNED conflicting header
            (all four validator keys, TwinSigner — bypassing the
            double-sign guard) for a fresh height: the gateway's witness
            cross-check must detect the divergence, roll back nothing
            into the shared store, demote the primary and promote an
            honest witness — and keep serving every other tenant
            throughout
  settle    the validator net must still agree; a fresh tenant asking
            about the forged height must get the REAL header

With --json the last stdout line carries `lite_bisections_per_sec`,
`lite_cache_hit_ratio`, `lite_verify_coalesce_ratio`,
`lite_sessions_sustained` and `lite_diverged_detect_ms` — the numbers
bench.py reports.
"""

import argparse
import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import tendermint_tpu.store  # noqa: E402,F401 — registers BlockMeta with the codec
import tendermint_tpu.types  # noqa: E402,F401 — registers Block types
from tendermint_tpu.chaos.checker import InvariantChecker  # noqa: E402
from tendermint_tpu.chaos.twin import TwinSigner  # noqa: E402
from tendermint_tpu.privval.file import FilePV  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable, make_response  # noqa: E402
from tendermint_tpu.tools import loadgen  # noqa: E402
from tendermint_tpu.types import (  # noqa: E402
    BlockID,
    Header,
    PartSetHeader,
    SignedHeader,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE  # noqa: E402


def rpc(port: int, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def scrape(checker: InvariantChecker, ports) -> None:
    for i, p in enumerate(ports):
        h = height_of(p)
        checker.observe_height(i, h)
        if h is None or h < 1:
            continue
        try:
            metas = from_jsonable(
                rpc(p, f"blockchain?min_height={max(1, h - 19)}&max_height={h}")["result"]
            )["block_metas"]
        except Exception:
            continue
        for meta in metas:
            checker.observe_block_hash(i, meta.header.height, meta.block_id.hash)


def spawn_node(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


class AdversarialPrimary:
    """A forwarding JSON-RPC proxy in front of node0.  Unarmed it is a
    byte-transparent relay; armed it answers `commit` for specific
    heights with a twin-signed conflicting header — the lying-primary
    attack the witness cross-check exists for."""

    def __init__(self, upstream_port: int):
        self.upstream = f"http://127.0.0.1:{upstream_port}/"
        self.forged = {}  # height -> SignedHeader (twin-signed)
        self.hijacked = 0
        self._runner = None
        self._session = None
        self.port = 0

    async def start(self, port: int) -> None:
        import aiohttp
        from aiohttp import web

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=10.0)
        )
        app = web.Application()
        app.router.add_post("/", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
        if self._runner is not None:
            await self._runner.cleanup()

    async def _handle(self, request):
        from aiohttp import web

        body = await request.read()
        if self.forged:
            try:
                req = json.loads(body)
            except ValueError:
                req = None
            if isinstance(req, dict) and req.get("method") == "commit":
                h = (req.get("params") or {}).get("height")
                sh = self.forged.get(h)
                if sh is not None:
                    self.hijacked += 1
                    return web.json_response(make_response(
                        req.get("id"), {"signed_header": sh, "canonical": True}
                    ))
        async with self._session.post(self.upstream, data=body) as r:
            return web.Response(body=await r.read(), content_type="application/json")


def forge_twin_header(homes, chain_id: str, real_sh, vset) -> SignedHeader:
    """The twin attack at header granularity: copy the real header at this
    height, flip its app_hash, and re-commit the new BlockID with ALL
    validator keys wrapped in TwinSigner (which signs anything, bypassing
    the last-sign-state guard a correct validator relies on)."""
    real = real_sh.header
    forged = Header(
        version_block=real.version_block,
        version_app=real.version_app,
        chain_id=real.chain_id,
        height=real.height,
        time_ns=real.time_ns,
        last_block_id=real.last_block_id,
        last_commit_hash=real.last_commit_hash,
        data_hash=real.data_hash,
        validators_hash=real.validators_hash,
        next_validators_hash=real.next_validators_hash,
        consensus_hash=real.consensus_hash,
        app_hash=b"\xde\xad\xbe\xef" * 8,
        last_results_hash=real.last_results_hash,
        evidence_hash=real.evidence_hash,
        proposer_address=real.proposer_address,
    )
    assert forged.hash() != real.hash()
    twins = []
    for home in homes:
        pv = FilePV.load(
            os.path.join(home, "config", "priv_validator_key.json"),
            os.path.join(home, "data", "priv_validator_state.json"),
        )
        twins.append(TwinSigner(pv))
    bid = BlockID(forged.hash(), PartSetHeader(1, forged.hash()))
    vs = VoteSet(chain_id, forged.height, 0, PRECOMMIT_TYPE, vset)
    for twin in twins:
        idx, _ = vset.get_by_address(twin.address())
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=forged.height,
            round=0,
            block_id=bid,
            timestamp_ns=real.time_ns + 1,
            validator_address=twin.address(),
            validator_index=idx,
        )
        twin.sign_vote(chain_id, v)
        vs.add_vote(v)
    return SignedHeader(forged, vs.make_commit())


async def lite_rpc(http, base: str, method: str, **params):
    async with http.post(f"http://{base}/", data=json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    )) as resp:
        return await resp.json()


async def run(args, homes, ports, procs, env) -> dict:
    import aiohttp

    from tendermint_tpu.lite2 import HTTPProvider

    checker = InvariantChecker(4)
    result = {}
    failures = []

    # -- startup ----------------------------------------------------------
    deadline = time.time() + 120.0
    while time.time() < deadline:
        hs = [height_of(p) for p in ports]
        if all(h is not None and h >= 4 for h in hs):
            break
        if any(p.poll() is not None for p in procs):
            raise RuntimeError("a node died during startup")
        await asyncio.sleep(0.5)
    else:
        raise RuntimeError(f"startup timeout: heights {[height_of(p) for p in ports]}")
    print(f"localnet ready, heights {[height_of(p) for p in ports]}")

    with open(os.path.join(homes[0], "config", "genesis.json")) as fh:
        chain_id = json.load(fh)["chain_id"]

    node0 = HTTPProvider(chain_id, f"127.0.0.1:{ports[0]}")
    root_sh = await node0.signed_header(2)
    trust_hash = root_sh.header.hash().hex()

    # -- adversarial proxy + gateway subprocess ----------------------------
    proxy = AdversarialPrimary(ports[0])
    await proxy.start(args.base_port + 90)
    ls_port = args.base_port + 91
    ls_log = open(os.path.join(os.path.dirname(homes[0]), "liteserve.log"), "ab")
    ls_proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "liteserve",
         "--chain-id", chain_id,
         "--primary", f"127.0.0.1:{proxy.port}",
         "--witnesses", ",".join(f"127.0.0.1:{p}" for p in ports[1:]),
         "--laddr", f"tcp://127.0.0.1:{ls_port}",
         "--height", "2", "--hash", trust_hash,
         "--witness-quorum", "2", "--witness-timeout", "5.0"],
        env=env, stdout=ls_log, stderr=subprocess.STDOUT,
    )
    ls_base = f"127.0.0.1:{ls_port}"

    http = aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=20.0))
    try:
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if ls_proc.poll() is not None:
                raise RuntimeError("liteserve died during startup (see liteserve.log)")
            try:
                res = await lite_rpc(http, ls_base, "lite_status")
                if "result" in res:
                    break
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.3)
        else:
            raise RuntimeError("liteserve startup timeout")
        print(f"liteserve ready on {ls_base}")

        # checker scraper underneath everything (executor: urllib is sync)
        stop = asyncio.Event()

        async def scraper():
            while not stop.is_set():
                await asyncio.get_event_loop().run_in_executor(
                    None, scrape, checker, ports
                )
                try:
                    await asyncio.wait_for(stop.wait(), 0.5)
                except asyncio.TimeoutError:
                    pass

        scr = asyncio.create_task(scraper())

        # -- phase 1: the tenant fleet ------------------------------------
        fleet = await loadgen.run_lite_load(
            ls_base,
            sessions=args.sessions,
            duration=args.load_duration,
            trust_height=2,
            trust_hash=trust_hash,
        )
        print(
            f"fleet: {fleet['lite_sessions_sustained']}/{fleet['lite_sessions']} "
            f"sessions sustained, {fleet['lite_bisections_per_sec']} verified "
            f"queries/s, hit ratio {fleet['lite_cache_hit_ratio']}, coalesce "
            f"ratio {fleet['lite_verify_coalesce_ratio']}, latency "
            f"{fleet['lite_commit_latency_ms']}"
        )

        # -- phase 2: the adversarial primary -----------------------------
        # pick a FRESH height (not yet in the gateway's verified span) and
        # wait for the chain to commit it
        status = (await lite_rpc(http, ls_base, "lite_status"))["result"]
        target = int(status["latest_trusted_height"]) + 3
        deadline = time.time() + 60.0
        while time.time() < deadline:
            tips = [h for h in (height_of(p) for p in ports) if h is not None]
            if tips and max(tips) >= target + 1:
                break
            await asyncio.sleep(0.3)
        real_sh = await node0.signed_header(target)
        vset = await node0.validator_set(target)
        forged = forge_twin_header(homes, chain_id, real_sh, vset)
        proxy.forged[target] = forged
        print(f"adversary armed: twin-signed conflicting header at height {target}")

        victim = (await lite_rpc(
            http, ls_base, "lite_session_new", trust_height=2, trust_hash=trust_hash,
        ))["result"]["session"]
        bystander = (await lite_rpc(
            http, ls_base, "lite_session_new", trust_height=2, trust_hash=trust_hash,
        ))["result"]["session"]

        t0 = time.monotonic()
        res = await lite_rpc(http, ls_base, "lite_commit", session=victim,
                             height=target)
        detect_ms = round((time.monotonic() - t0) * 1e3, 1)
        served_real = False
        if "result" in res:
            got = from_jsonable(res["result"])["signed_header"]
            served_real = got.header.hash() == real_sh.header.hash()
        status = (await lite_rpc(http, ls_base, "lite_status"))["result"]
        verify = status["verify"]
        print(
            f"adversary phase: detect+recover {detect_ms} ms, diverged "
            f"{verify['diverged_detected']}, primary replacements "
            f"{verify['primary_replacements']} (demoted: "
            f"{verify['demoted_primaries']}), proxy hijacks {proxy.hijacked}, "
            f"served real header: {served_real}"
        )

        # bystander keeps being served during/after the incident, and a
        # FRESH tenant asking the forged height gets the real chain
        by = await lite_rpc(http, ls_base, "lite_commit", session=bystander,
                            height=target - 1)
        fresh = (await lite_rpc(
            http, ls_base, "lite_session_new", trust_height=2, trust_hash=trust_hash,
        ))["result"]["session"]
        re_res = await lite_rpc(http, ls_base, "lite_commit", session=fresh,
                                height=target)
        re_real = (
            "result" in re_res
            and from_jsonable(re_res["result"])["signed_header"].header.hash()
            == real_sh.header.hash()
        )

        # -- settle -------------------------------------------------------
        await asyncio.sleep(args.settle)
        stop.set()
        await scr

        # -- verdict ------------------------------------------------------
        if checker.violations:
            failures.append(f"invariant violations: {checker.violations}")
        if fleet["lite_sessions_sustained"] < args.sessions:
            failures.append(
                f"only {fleet['lite_sessions_sustained']}/{args.sessions} "
                "sessions sustained"
            )
        if fleet["lite_cache_hit_ratio"] <= 0.5:
            failures.append(
                f"cache hit ratio {fleet['lite_cache_hit_ratio']} <= 0.5: the "
                "shared store is not absorbing the fan-in"
            )
        if fleet["lite_verify_coalesce_ratio"] <= 0:
            failures.append("no verification coalescing observed")
        if fleet["lite_transport_errors"] > 0.05 * max(1, fleet["lite_requests_completed"]):
            failures.append(
                f"{fleet['lite_transport_errors']} transport errors (silent drops)"
            )
        if proxy.hijacked <= 0:
            failures.append("the adversarial proxy was never consulted")
        if verify["diverged_detected"] < 1:
            failures.append("divergence was not detected")
        if verify["primary_replacements"] < 1:
            failures.append("the lying primary was not demoted")
        if not served_real:
            failures.append(
                "the victim tenant was not served the real header after recovery"
            )
        if "result" not in by:
            failures.append(f"bystander tenant failed during the incident: {by}")
        if not re_real:
            failures.append("a fresh tenant saw poisoned state at the forged height")
        if len(checker.agreed_heights()) < 3:
            failures.append("too few heights cross-checked for agreement")

        result = {
            "metric": "lite_smoke",
            "lite_bisections_per_sec": fleet["lite_bisections_per_sec"],
            "lite_cache_hit_ratio": fleet["lite_cache_hit_ratio"],
            "lite_verify_coalesce_ratio": fleet["lite_verify_coalesce_ratio"],
            "lite_sessions_sustained": fleet["lite_sessions_sustained"],
            "lite_diverged_detect_ms": detect_ms,
            "lite_commit_latency_ms": fleet["lite_commit_latency_ms"],
            "lite_requests_completed": fleet["lite_requests_completed"],
            "lite_throttled": fleet["lite_throttled"],
            "diverged_detected": verify["diverged_detected"],
            "primary_replacements": verify["primary_replacements"],
            "proxy_hijacks": proxy.hijacked,
            "heights": [height_of(p) for p in ports],
            **checker.summary(),
        }
    finally:
        await http.close()
        await node0.close()
        if ls_proc.poll() is None:
            ls_proc.send_signal(signal.SIGTERM)
            try:
                ls_proc.wait(10)
            except subprocess.TimeoutExpired:
                ls_proc.kill()
        await proxy.stop()

    return result, failures


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-lite")
    ap.add_argument("--base-port", type=int, default=33656)
    ap.add_argument("--sessions", type=int, default=64)
    ap.add_argument("--load-duration", type=float, default=12.0)
    ap.add_argument("--settle", type=float, default=4.0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build,
         "--base-port", str(args.base_port), "--fast"],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [args.base_port + 10 * i + 1 for i in range(4)]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [spawn_node(h, env) for h in homes]

    ok = False
    result = {}
    try:
        result, failures = asyncio.run(run(args, homes, ports, procs, env))
        if failures:
            print("LITE SMOKE FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
        else:
            print(
                f"lite smoke ok: {result['lite_sessions_sustained']} sessions "
                f"sustained at {result['lite_bisections_per_sec']} verified "
                f"queries/s, hit ratio {result['lite_cache_hit_ratio']}, "
                f"coalesce ratio {result['lite_verify_coalesce_ratio']}, "
                f"divergence detected+recovered in "
                f"{result['lite_diverged_detect_ms']} ms, agreement over "
                f"{result.get('heights_checked', 0)} heights"
            )
            ok = True
    except Exception as e:  # noqa: BLE001 — the rig reports, then fails
        print(f"LITE SMOKE ERROR: {e!r}", file=sys.stderr)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
