#!/usr/bin/env python
"""Chaos smoke: the scripted partition/kill/twin scenario against a real
4-validator multi-process localnet — the `make chaos-smoke` acceptance rig.

Scenario (seeded; the SAME seed replays the SAME fault timeline — the
script parses it twice and asserts identical fingerprints):

    twin 0                       node0 double-signs prevotes from genesis
    partition 0,1|2,3 @2~0.5     no side has +2/3 -> commits MUST stop
    heal @8~0.5                  commits must resume within the bound
    kill 2 @11                   SIGKILL mid-consensus (sqlite: durable)
    restart 2 @13                crash recovery + catchup via gossip

Faults are staged through each node's config-gated `unsafe_chaos_*` RPC
routes (partition = drop=1.0 set symmetrically on both sides' outbound
links) and OS signals; the invariant checker (chaos/checker.py — the same
code the in-process tier-1 tests use) scrapes `/status` and `/blockchain`
from every node each poll and accumulates violations:

  - agreement: no two nodes ever commit different hashes at one height
  - no height regression per node (sqlite backend: strict across restart)
  - commits stop during the partition (a "partition" that doesn't stall
    a 2|2 split means the fault layer isn't injecting)
  - commits resume within --recovery-bound after heal AND after restart
  - accountability: the twin's DuplicateVoteEvidence is committed into a
    block AND surfaces via BeginBlock byzantine_validators (the kvstore
    app records delivered addresses under the `__byzantine__` key)
  - self-diagnosis (libs/watchdog.py): every non-twin node's /health must
    be alarm-free through the pre-partition quiet phase (zero false
    alarms), the consensus_stall alarm must FIRE on a non-twin node while
    the partition holds (`health_detect_latency_ms`), and by the end of
    the recovery budget every live non-twin node must have CLEARED it —
    the node noticed the fault and noticed the recovery, by itself.
    (The twin is exempt: it reference-correctly halts on its own
    conflict, and its stall alarm firing is the watchdog being right.)

  - byzantine trace context: the twin forges a huge hop count and a
    far-future origin timestamp on its equivocation frames; at least one
    honest receiver must CLAMP them (gossip.hop `clamped`, counted via
    watermarked polls during the run) — forged wire trace fields are
    never trusted into skew estimation

With --json the last stdout line carries `chaos_partition_recovery_ms`
(heal -> first new commit, wall ms) — the number bench.py reports.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import tendermint_tpu.store  # noqa: E402,F401 — registers BlockMeta with the codec
import tendermint_tpu.types  # noqa: E402,F401 — registers Block/evidence types
from tendermint_tpu.chaos.checker import InvariantChecker, RecoveryTimer  # noqa: E402
from tendermint_tpu.chaos.scenario import Scenario  # noqa: E402
from tendermint_tpu.rpc.jsonrpc import from_jsonable  # noqa: E402

SCENARIO = """
twin 0
partition 0,1|2,3 @2~0.5
heal @8~0.5
kill 2 @11
restart 2 @13
"""


def rpc(port: int, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.load(r)


def rpc_call(port: int, method: str, **params):
    qs = urllib.parse.urlencode({k: str(v) for k, v in params.items()})
    return rpc(port, f"{method}?{qs}" if qs else method)


def height_of(port: int):
    try:
        return int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
    except Exception:
        return None


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-chaos")
    ap.add_argument("--base-port", type=int, default=30656)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--recovery-bound", type=float, default=30.0,
                    help="max seconds from heal/restart to the next commit")
    ap.add_argument("--budget", type=float, default=90.0,
                    help="seconds after the last fault for evidence + recovery")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    # determinism gate: same text + seed => same resolved timeline
    scenario = Scenario.parse(SCENARIO, seed=args.seed)
    assert scenario.fingerprint() == Scenario.parse(SCENARIO, seed=args.seed).fingerprint(), \
        "scenario resolution is not deterministic"
    timeline = scenario.timeline()
    print(f"scenario fingerprint {scenario.fingerprint()[:16]} (seed {args.seed}):")
    for ev in timeline:
        print(f"  {ev.describe()}")

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--validators", "4", "--output", build, "--base-port", str(args.base_port),
         "--fast", "--db-backend", "sqlite",
         "--chaos", "--chaos-seed", str(args.seed), "--twin", "0"],
        check=True, cwd=REPO,
    )
    homes = [os.path.join(build, f"node{i}") for i in range(4)]
    ports = [args.base_port + 10 * i + 1 for i in range(4)]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_tendermint_tpu")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    procs = [spawn(h, env) for h in homes]

    checker = InvariantChecker(4, liveness_exempt=[0])  # twin halts by design
    # heal recovery = first NEW commit anywhere (tip advance);
    # restart recovery = every live non-twin node past the pre-restart tip
    heal_timer = RecoveryTimer()
    restart_timer = RecoveryTimer()
    result = {}
    ok = False
    try:
        # readiness: every RPC answers; every NON-TWIN node commits ≥ 1
        # (the twin may reference-correctly halt within its first heights)
        deadline = time.time() + 120.0
        while time.time() < deadline:
            hs = [height_of(p) for p in ports]
            if all(h is not None for h in hs) and all(h >= 1 for h in hs[1:]):
                break
            if any(p.poll() is not None for p in procs):
                print("a node died during startup", file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print(f"startup timeout: heights {[height_of(p) for p in ports]}",
                  file=sys.stderr)
            return 1
        node_ids = [rpc(p, "status")["result"]["node_info"]["id"] for p in ports]
        twin_addr = from_jsonable(
            rpc(ports[0], "status")["result"]["validator_info"]["address"]
        )
        print(f"localnet ready, heights {[height_of(p) for p in ports]}; "
              f"twin addr {twin_addr.hex()[:12]}")

        live = [True] * 4

        # watchdog observation state: quiet -> partition -> post_heal
        hstate = {
            "phase": "quiet",
            "t_partition": None,
            "detect_t": None,
            "quiet_alarms": set(),
            "clear_t": None,
        }

        def health_of(port):
            try:
                return rpc(port, "health")["result"]
            except Exception:
                return None

        def poll_health():
            """Non-twin /health sampling: quiet-phase alarms are false
            positives; the first consensus_stall during the partition is
            the detection landmark; all-clear is tracked for the end."""
            stall_free = True
            for i, p in enumerate(ports):
                if i == 0 or not live[i]:
                    continue
                h = health_of(p)
                if h is None:
                    stall_free = False  # unreachable ≠ clear
                    continue
                alarms = set(h.get("alarms", {}))
                if hstate["phase"] == "quiet" and alarms:
                    hstate["quiet_alarms"].update(f"node{i}:{a}" for a in alarms)
                if (
                    hstate["phase"] == "partition"
                    and hstate["detect_t"] is None
                    and "consensus_stall" in alarms
                ):
                    hstate["detect_t"] = time.time()
                    print(
                        f"  watchdog: node{i} raised consensus_stall "
                        f"{hstate['detect_t'] - hstate['t_partition']:.1f}s "
                        f"after the partition"
                    )
                if "consensus_stall" in alarms:
                    stall_free = False
            return stall_free

        # wire-level trace forensics: the twin forges byzantine trace
        # context (huge hop count, far-future origin timestamp) on its
        # equivocation frames; honest receivers must CLAMP and count, never
        # trust it into skew estimation.  Polled watermarked DURING the run
        # (throttled) so ring eviction can't hide early forgeries.
        trace_state = {"wm": {}, "clamps": 0, "hops": 0, "last_t": 0.0}

        def poll_trace_clamps():
            if time.time() - trace_state["last_t"] < 2.0:
                return
            trace_state["last_t"] = time.time()
            for i, p in enumerate(ports):
                if i == 0 or not live[i]:
                    continue
                try:
                    snap = rpc_call(
                        p, "dump_flight_recorder",
                        since=trace_state["wm"].get(i, 0), kinds="gossip.hop",
                    )["result"]
                except Exception:
                    continue
                trace_state["wm"][i] = snap.get(
                    "next_seq", trace_state["wm"].get(i, 0)
                )
                evs = snap.get("events", [])
                trace_state["hops"] += len(evs)
                trace_state["clamps"] += sum(
                    1 for ev in evs if ev.get("clamped")
                )

        def scrape():
            hs = []
            for i, p in enumerate(ports):
                h = height_of(p)
                hs.append(h)
                checker.observe_height(i, h)
                if h is None or h < 1:
                    continue
                try:
                    metas = from_jsonable(
                        rpc(p, f"blockchain?min_height={max(1, h - 19)}&max_height={h}")
                        ["result"]
                    )["block_metas"]
                except Exception:
                    continue
                for meta in metas:
                    checker.observe_block_hash(i, meta.header.height, meta.block_id.hash)
            known = [h for h in hs if h is not None]
            if known:
                heal_timer.observe(max(known))
            live_non_twin = [h for j, h in enumerate(hs)
                             if j != 0 and live[j] and h is not None]
            if live_non_twin and all(
                live[j] and hs[j] is not None for j in range(1, 4)
            ):
                restart_timer.observe(min(live_non_twin))

        def tip_of(idxs):
            """Max known height over the given node indices; falls back to
            the checker's last observations so a poll where every RPC
            times out (loaded CI box) degrades instead of crashing."""
            known = [h for h in (height_of(ports[i]) for i in idxs) if h is not None]
            if known:
                return max(known)
            seen = [checker.last_height.get(i) for i in idxs]
            return max((h for h in seen if h is not None), default=1)

        # -- execute the timeline, scraping between events ------------------
        t0 = time.time()
        stall_window = None  # (t_start, max_height_at_start)
        for ev in timeline:
            while time.time() < t0 + ev.t:
                scrape()
                poll_health()
                poll_trace_clamps()
                time.sleep(0.4)
            print(f"+{time.time() - t0:6.2f}s executing {ev.describe()}")
            if ev.action == "twin":
                continue  # config-installed from genesis
            if ev.action == "partition":
                groups = ev.args["groups"]
                for gi, g1 in enumerate(groups):
                    for g2 in groups[gi + 1:]:
                        for a in g1:
                            for b in g2:
                                rpc_call(ports[a], "unsafe_chaos_link",
                                         peer_id=node_ids[b], drop=1.0)
                                rpc_call(ports[b], "unsafe_chaos_link",
                                         peer_id=node_ids[a], drop=1.0)
                time.sleep(1.0)  # drain in-flight gossip
                stall_window = (time.time(), tip_of(range(4)))
                hstate["phase"] = "partition"
                hstate["t_partition"] = time.time()
            elif ev.action == "heal":
                # the stall assertion: a 2|2 split has no +2/3 side, so at
                # most one in-flight height may have landed since the cut
                if stall_window is not None:
                    tip = tip_of(range(4))
                    if tip > stall_window[1] + 1:
                        checker.violations.append(
                            f"commits continued during partition: "
                            f"{stall_window[1]} -> {tip}"
                        )
                    print(f"  partition stalled the net at ~{stall_window[1]} "
                          f"for {time.time() - stall_window[0]:.1f}s (tip {tip})")
                # detection must have happened while the cut still held
                if hstate["detect_t"] is None:
                    poll_health()  # one last chance at the boundary
                hstate["phase"] = "post_heal"
                baseline = tip_of(range(4))
                for i, p in enumerate(ports):
                    if live[i]:
                        rpc_call(p, "unsafe_chaos_heal")
                heal_timer.mark("heal", baseline)
            elif ev.action == "kill":
                i = ev.args["node"]
                procs[i].send_signal(signal.SIGKILL)
                procs[i].wait(10)
                live[i] = False
            elif ev.action == "restart":
                i = ev.args["node"]
                baseline = tip_of([j for j in range(1, 4) if live[j]])
                procs[i] = spawn(homes[i], env)
                live[i] = True
                restart_timer.mark("restart", baseline)

        # -- recovery + accountability within the budget --------------------
        evidence_height = None
        byz_delivered = False
        deadline = time.time() + args.budget
        while time.time() < deadline:
            scrape()
            poll_trace_clamps()
            if poll_health() and hstate["clear_t"] is None:
                hstate["clear_t"] = time.time()
                print(f"  watchdog: consensus_stall clear on every live "
                      f"non-twin node at +{time.time() - t0:.1f}s")
            if evidence_height is None:
                tip = height_of(ports[1]) or 0
                for h in range(1, tip + 1):
                    try:
                        blk = from_jsonable(
                            rpc(ports[1], f"block?height={h}")["result"]
                        )["block"]
                    except Exception:
                        continue
                    if blk is not None and blk.evidence:
                        assert blk.evidence[0].address() == twin_addr, \
                            "committed evidence names the wrong validator"
                        evidence_height = h
                        break
            if not byz_delivered:
                try:
                    res = rpc_call(ports[1], "abci_query", data='"__byzantine__"')
                    val = from_jsonable(res["result"]["response"]).get("value") or b""
                    byz_delivered = twin_addr.hex().encode() in val
                except Exception:
                    pass
            if (not heal_timer.unrecovered() and not restart_timer.unrecovered()
                    and evidence_height is not None and byz_delivered
                    and hstate["clear_t"] is not None):
                break
            time.sleep(0.4)

        detect_ms = (
            round((hstate["detect_t"] - hstate["t_partition"]) * 1000, 1)
            if hstate["detect_t"] is not None and hstate["t_partition"] is not None
            else -1.0
        )
        result = {
            "metric": "chaos_smoke",
            "fingerprint": scenario.fingerprint(),
            "seed": args.seed,
            "chaos_partition_recovery_ms": round(heal_timer.recovery_ms.get("heal", -1.0), 1),
            "restart_recovery_ms": round(restart_timer.recovery_ms.get("restart", -1.0), 1),
            "health_detect_latency_ms": detect_ms,
            "health_quiet_alarms": sorted(hstate["quiet_alarms"]),
            "health_stall_cleared": hstate["clear_t"] is not None,
            "evidence_height": evidence_height,
            "byzantine_validators_delivered": byz_delivered,
            "heights": [height_of(p) for p in ports],
            "twin_equivocations": rpc(ports[0], "unsafe_chaos_status")
            ["result"]["equivocations"],
            "trace_clamps": trace_state["clamps"],
            "gossip_hop_events": trace_state["hops"],
            **checker.summary(),
        }
        failures = []
        if checker.violations:
            failures.append(f"invariant violations: {checker.violations}")
        for name, tmr in (("heal", heal_timer), ("restart", restart_timer)):
            ms = tmr.recovery_ms.get(name)
            if ms is None:
                failures.append(f"net never recovered after {name}")
            elif ms > args.recovery_bound * 1000:
                failures.append(f"{name} recovery {ms:.0f}ms exceeds bound")
        if evidence_height is None:
            failures.append("twin evidence never committed into a block")
        if not byz_delivered:
            failures.append("byzantine_validators never delivered via BeginBlock")
        if len(checker.agreed_heights()) < 3:
            failures.append("too few heights cross-checked for agreement")
        if hstate["detect_t"] is None:
            failures.append(
                "watchdog never raised consensus_stall during the partition"
            )
        if hstate["quiet_alarms"]:
            failures.append(
                f"watchdog false alarms during the quiet phase: "
                f"{sorted(hstate['quiet_alarms'])}"
            )
        if hstate["clear_t"] is None:
            failures.append(
                "watchdog consensus_stall never cleared on every live "
                "non-twin node after recovery"
            )
        if trace_state["clamps"] < 1:
            failures.append(
                "no clamped trace context observed: the twin's forged "
                "hop/origin fields were either not sent or TRUSTED by a "
                "receiver"
            )
        if failures:
            print("CHAOS SMOKE FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
        else:
            print(
                f"chaos smoke ok: agreement over "
                f"{len(checker.agreed_heights())} heights, heal recovery "
                f"{result['chaos_partition_recovery_ms']:.0f} ms, restart "
                f"recovery {result['restart_recovery_ms']:.0f} ms, stall "
                f"alarm in {result['health_detect_latency_ms']:.0f} ms "
                f"(0 false alarms, cleared after heal), twin evidence "
                f"committed at height {evidence_height} and delivered "
                f"via BeginBlock"
            )
            ok = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
