#!/usr/bin/env python
"""BLS aggregate-commit smoke: a BLS12-381 localnet must commit blocks
whose stored commits carry ONE aggregate signature + signer bitmap — the
`make bls-smoke` acceptance rig for the crypto/bls subsystem.

Flow:
  1. generate a 3-validator `testnet --fast --key-type bls12381` tree
     (BLS keys everywhere, genesis validators carry proofs of possession);
  2. run the validators as OS processes until ≥ --min-heights blocks
     commit;
  3. fetch every canonical commit below the tip from EVERY node's
     `/commit` RPC and require the aggregate representation: a 96-byte
     `agg_sig` + `signers` bitmap with ≥ 2/3 of the set, and NO per-vote
     `signatures` array — one classic commit anywhere fails the smoke
     (aggregation silently disabled is exactly the regression this rig
     exists to catch);
  4. spawn a 4th EMPTY non-validator node that fastsyncs from genesis —
     its replay verifies the same aggregate commits through
     `fastsync.processor.verify_commit_run`'s one-pairing batch — and
     require it to catch up within the budget.

With --json the last stdout line carries `bls_commit_bytes` (measured
canonical commit size) and `commits_per_sec` — the numbers bench.py
reports next to the ed25519 baseline.
"""

import argparse
import base64
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request

# bls_tier gauge values (libs/metrics.VerifyMetrics): 1=C extension, 2=pure
BLS_TIER_C = 1

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from tendermint_tpu.config import load_config, save_config  # noqa: E402

BLS_SIG_LEN = 96


def rpc(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/{path}", timeout=3) as r:
        return json.load(r)


def heights(ports):
    out = []
    for p in ports:
        try:
            out.append(int(rpc(p, "status")["result"]["sync_info"]["latest_block_height"]))
        except Exception:
            out.append(-1)
    return out


def spawn(home: str, env) -> subprocess.Popen:
    log = open(os.path.join(home, "node.log"), "wb")
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def rpc_port_of(home: str) -> int:
    cfg = load_config(os.path.join(home, "config", "config.toml"), home=home)
    return int(cfg.rpc.laddr.rsplit(":", 1)[1])


def enable_prometheus(home: str, port: int) -> None:
    """Turn the node's metrics endpoint on so the rig can assert WHICH
    BLS tier carried the net — same node-telemetry pattern as the verify
    engine's backend_tier gauge."""
    path = os.path.join(home, "config", "config.toml")
    cfg = load_config(path, home=home)
    cfg.instrumentation.prometheus = True
    cfg.instrumentation.prometheus_listen_addr = f"127.0.0.1:{port}"
    save_config(cfg, path)


def scrape_bls_tier(port: int):
    """The node's tendermint_verify_bls_tier gauge value, or None."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=3
        ) as r:
            body = r.read().decode("utf-8", "replace")
    except Exception:
        return None
    for line in body.splitlines():
        if line.startswith("tendermint_verify_bls_tier{"):
            try:
                return int(float(line.rsplit(" ", 1)[1]))
            except ValueError:
                return None
    return None


def have_toolchain() -> bool:
    return shutil.which("cc") is not None


def check_commit(commit: dict, n_vals: int) -> int:
    """Assert one commit dict is the aggregate representation; returns its
    canonical byte size (bitmap + agg_sig + ids)."""
    if "signatures" in commit:
        raise AssertionError(
            f"commit at height {commit.get('height')} carries per-vote "
            "signatures — aggregation did not engage"
        )
    sig = commit.get("agg_sig")
    if isinstance(sig, dict):  # jsonable bytes: {"@b": base64}
        sig = base64.b64decode(sig["@b"])
    if not sig or len(sig) != BLS_SIG_LEN:
        raise AssertionError(f"bad agg_sig in commit: {commit}")
    signers = commit.get("signers")
    if isinstance(signers, dict):
        signers = base64.b64decode(signers["@b"])
    if not signers:
        raise AssertionError(f"missing signer bitmap in commit: {commit}")
    # BitArray wire layout: 4-byte big-endian bit count + bit bytes
    nbits = int.from_bytes(signers[:4], "big")
    popcount = sum(bin(b).count("1") for b in signers[4:])
    if nbits != n_vals or popcount * 3 <= n_vals * 2:
        raise AssertionError(
            f"signer bitmap {popcount}/{nbits} below +2/3 of {n_vals}"
        )
    # canonical size: what AggregateCommit.encode() measures — block id
    # (~75B) + bitmap + one 96B signature, O(1) in validator count
    bid = commit["block_id"]
    bid_hash = base64.b64decode(bid["hash"]["@b"])
    psh_hash = base64.b64decode(bid["parts"]["hash"]["@b"])
    return len(sig) + len(signers) + len(bid_hash) + len(psh_hash) + 24


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-dir", default="./build-bls")
    ap.add_argument("--validators", type=int, default=3)
    ap.add_argument("--base-port", type=int, default=30656)
    ap.add_argument("--min-heights", type=int, default=5)
    ap.add_argument("--budget", type=float, default=240.0,
                    help="seconds for startup + min-heights commits + joiner catchup")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    build = os.path.abspath(args.build_dir)
    if os.path.isdir(build):
        shutil.rmtree(build)
    n = args.validators
    rc = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "-v", str(n), "-o", build, "--fast", "--key-type", "bls12381",
         "--base-port", str(args.base_port)],
    ).returncode
    if rc != 0:
        print("testnet generation failed", file=sys.stderr)
        return 1

    homes = [os.path.join(build, f"node{i}") for i in range(n)]
    ports = [rpc_port_of(h) for h in homes]
    metric_ports = [args.base_port + 900 + i for i in range(n)]
    for home, mport in zip(homes, metric_ports):
        enable_prometheus(home, mport)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [spawn(h, env) for h in homes]
    joiner_proc = None
    ok = False
    result = {}
    deadline = time.time() + args.budget
    try:
        # ---- phase 1: the BLS net must commit blocks --------------------
        t0 = time.time()
        while time.time() < deadline:
            hs = heights(ports)
            if min(hs) >= args.min_heights:
                break
            if any(p.poll() is not None for p in procs):
                print("a validator process exited", file=sys.stderr)
                return 1
            time.sleep(1.0)
        else:
            print(f"budget exhausted before {args.min_heights} commits: "
                  f"{heights(ports)}", file=sys.stderr)
            return 1
        elapsed = time.time() - t0
        hs = heights(ports)
        print(f"BLS net at heights {hs} after {elapsed:.1f}s")

        # ---- tier assertion: the fast tier must have carried the net ----
        # Every node exports tendermint_verify_bls_tier (1=C, 2=pure).  A
        # host with a working toolchain running the ~460 ms pure pairing
        # is exactly the silent regression this gate exists to catch; a
        # toolchain-less host passes on the pure tier by design.
        tiers = [scrape_bls_tier(mp) for mp in metric_ports]
        print(f"bls tier per node (1=C, 2=pure): {tiers}")
        if any(t is None for t in tiers):
            print("could not scrape tendermint_verify_bls_tier from every "
                  f"node: {tiers}", file=sys.stderr)
            return 1
        if have_toolchain() and any(t != BLS_TIER_C for t in tiers):
            print(f"toolchain present but the C pairing tier did not engage "
                  f"(tiers {tiers})", file=sys.stderr)
            return 1

        # ---- phase 2: every canonical commit must be aggregate ----------
        sizes = []
        checked = 0
        for port in ports:
            tip = int(rpc(port, "status")["result"]["sync_info"]["latest_block_height"])
            for h in range(2, tip):  # canonical commits only (below tip)
                sh = rpc(port, f"commit?height={h}")["result"]["signed_header"]
                commit = sh["commit"]
                sizes.append(check_commit(commit, n))
                checked += 1
        if not checked:
            print("no canonical commits to check", file=sys.stderr)
            return 1
        size = max(sizes)
        print(f"checked {checked} stored commits across {n} nodes: all "
              f"aggregate (ONE {BLS_SIG_LEN}B signature + bitmap, "
              f"~{size}B canonical)")

        # ---- phase 3: empty joiner fastsyncs over aggregate commits -----
        joiner = os.path.join(build, "joiner")
        jport = args.base_port + 10 * n + 1
        rc = subprocess.run(
            [sys.executable, "-m", "tendermint_tpu.cli", "--home", joiner, "init",
             "--chain-id", "ignored"],
            stdout=subprocess.DEVNULL,
        ).returncode
        if rc != 0:
            print("joiner init failed", file=sys.stderr)
            return 1
        # the joiner shares the net's genesis (and so its PoP-checked BLS
        # validator set) but holds no validator key of its own
        shutil.copy(os.path.join(homes[0], "config", "genesis.json"),
                    os.path.join(joiner, "config", "genesis.json"))
        jcfg = load_config(os.path.join(joiner, "config", "config.toml"), home=joiner)
        src = load_config(os.path.join(homes[0], "config", "config.toml"), home=homes[0])
        jcfg.base.chain_id = src.base.chain_id
        jcfg.base.fast_sync = True
        jcfg.base.db_backend = "memdb"
        jcfg.tpu.enabled = False
        jcfg.p2p.laddr = f"tcp://127.0.0.1:{jport - 1}"
        jcfg.rpc.laddr = f"tcp://127.0.0.1:{jport}"
        jcfg.p2p.persistent_peers = src.p2p.persistent_peers
        jcfg.p2p.allow_duplicate_ip = True
        save_config(jcfg, os.path.join(joiner, "config", "config.toml"))
        joiner_proc = spawn(joiner, env)
        target = min(heights(ports))
        while time.time() < deadline:
            jh = heights([jport])[0]
            if jh >= target:
                break
            if joiner_proc.poll() is not None:
                print("joiner process exited", file=sys.stderr)
                return 1
            time.sleep(1.0)
        else:
            print(f"joiner stuck at {heights([jport])[0]} (target {target}): "
                  "fastsync over aggregate commits failed", file=sys.stderr)
            return 1
        print(f"joiner fastsynced to height {heights([jport])[0]} "
              f"(target {target}) — aggregate commits replayed")

        result = {
            "bls_commit_bytes": size,
            "bls_commits_checked": checked,
            "bls_tier": "c" if tiers[0] == BLS_TIER_C else "pure",
            "commits_per_sec": round(min(hs) / elapsed, 3),
            "heights": hs,
            "validators": n,
        }
        ok = True
    finally:
        for p in procs + ([joiner_proc] if joiner_proc else []):
            p.send_signal(signal.SIGTERM)
        for p in procs + ([joiner_proc] if joiner_proc else []):
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
    if args.json and result:
        print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
